"""Columnar on-disk dataset cache: the package's shared data plane.

BENCH_builder_r06 spent 356 s of its 480 s wall generating synthetic
data — 74% of the benchmark measured datagen, not fitting.  This module
replaces every ad-hoc in-memory/private datagen path (bench.py's ``/tmp``
npy cache, serve loadgen's inline demo batch, streaming's hand-rolled
frames) with ONE cache of memmap column shards:

* **Layout** — one directory per dataset under :func:`default_root`,
  keyed by (generator, shape, seed, shard width, datagen fingerprint).
  Inside: ``spec.json`` (identity, written first), ``ds.npy`` (shared
  calendar, float64), preallocated float32 column files ``y.npy`` /
  ``mask.npy`` / ``reg.npy`` / ``cap.npy`` in exactly the layout
  ``orchestrate._load_data`` mmaps — a complete dataset dir IS a valid
  orchestrate ``--data`` dir — plus one ``shardok_<lo>_<hi>.json``
  sentinel per landed shard and a final ``plane_manifest.json``.

* **Lifecycle** — column files are preallocated memmaps filled shard by
  shard; a shard's rows become visible ONLY once its sentinel (written
  atomically, payload CRCs inside) lands, and the manifest (atomic,
  written last after sentinel coverage is complete) marks the dataset
  warm.  Readers never trust bytes a sentinel doesn't cover, so a torn
  shard can never be consumed; concurrent producers are safe because
  generation is deterministic — racers write identical bytes and the
  last identical sentinel wins whole.

* **Determinism** — generation is block-seeded
  (:data:`~tsspark_tpu.data.datasets.SEED_BLOCK`): rows [lo, hi) of a
  dataset are bitwise-identical whether produced by one process, a
  shard pool, or a fit worker self-healing a stalled ingest
  (``tests/test_plane.py`` pins cache == direct generation).

* **Overlap** — :mod:`tsspark_tpu.data.ingest` produces shards in a
  background process pool while orchestrate fit workers consume
  already-landed coverage (:func:`ready_coverage`), so a cold run
  starts fitting before ingestion finishes and a warm run is pure
  memmap reads.

Scenario packs (irregular cadence, missing windows, cold start, M5
store->dept->item hierarchy) are first-class named datasets behind the
same manifest — see :data:`GENERATORS`.
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import os
import tempfile
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tsspark_tpu.data import datasets
from tsspark_tpu.data.datasets import SeriesBatch
from tsspark_tpu.utils.atomic import atomic_write

#: Cache-format revision: bump when the on-disk layout (NOT the data)
#: changes incompatibly; part of every spec record.
PLANE_VERSION = 1

#: Default I/O shard width — a multiple of every pow-2 claim width the
#: orchestrator's autotuner dispatches (floor 128, historical cap 1024),
#: so fit claims always nest inside whole shards.
DEFAULT_SHARD_ROWS = 1024

#: Column files, in orchestrate._DATA_FIELDS naming (float32 on disk;
#: ``ds.npy`` rides separately and stays float64).
COLUMN_FIELDS = ("y", "mask", "reg", "cap")

_SPEC_FILE = "spec.json"
_MANIFEST_FILE = "plane_manifest.json"

#: name -> row generator ``fn(lo, hi, n_timesteps, seed) -> SeriesBatch``.
#: Every generator is block-seeded: rows are independent of the total
#: series count, so datasets extend without regeneration.
GENERATORS: Dict[str, Callable[..., SeriesBatch]] = {
    "m5": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="base"),
    "m5_irregular": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="irregular"),
    "m5_missing_windows": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="missing_windows"),
    "m5_cold_start": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="cold_start"),
    "m5_hier": lambda lo, hi, t, seed: datasets.m5_rows(
        lo, hi, n_days=t, seed=seed, scenario="hier"),
    "demo_weekly": lambda lo, hi, t, seed: datasets.demo_weekly_rows(
        lo, hi, n_steps=t, seed=seed),
}


@dataclasses.dataclass(frozen=True)
class DatasetSpec:
    """Identity of one cached dataset (the manifest key)."""

    generator: str
    n_series: int
    n_timesteps: int
    seed: int = 2
    shard_rows: int = DEFAULT_SHARD_ROWS

    def __post_init__(self):
        if self.generator not in GENERATORS \
                and not self.generator.startswith("import:"):
            raise ValueError(
                f"unknown generator {self.generator!r}; known: "
                f"{sorted(GENERATORS)} (or 'import:<name>')"
            )
        if self.n_series <= 0 or self.n_timesteps <= 0:
            raise ValueError("n_series and n_timesteps must be positive")
        if self.shard_rows <= 0:
            raise ValueError("shard_rows must be positive")

    def cache_key(self) -> str:
        return (
            f"{self.generator}_{self.n_series}x{self.n_timesteps}"
            f"_s{self.seed}_r{self.shard_rows}_{dataset_fingerprint()}"
        )

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "DatasetSpec":
        return cls(**{
            k: d[k] for k in
            ("generator", "n_series", "n_timesteps", "seed", "shard_rows")
        })


_FP_CACHE: Dict[str, str] = {}


def dataset_fingerprint() -> str:
    """Hash of the WHOLE data package (datasets + loaders + plane +
    ingest): a change to any of them rotates every cache key, so a
    loader/plane change can never serve stale cached arrays (ISSUE 9 —
    the old bench fingerprint hashed ``datasets.py`` alone)."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    if pkg in _FP_CACHE:
        return _FP_CACHE[pkg]
    h = hashlib.md5()
    h.update(str(PLANE_VERSION).encode())
    for path in sorted(glob.glob(os.path.join(pkg, "*.py"))):
        with open(path, "rb") as fh:
            h.update(fh.read())
    _FP_CACHE[pkg] = h.hexdigest()[:8]
    return _FP_CACHE[pkg]


def default_root() -> str:
    """The shared cache root: ``$TSSPARK_DATA_ROOT`` or a stable temp
    location (all subsystems — bench, serve loadgen, streaming replay —
    default here, which is what makes the plane SHARED)."""
    return os.environ.get("TSSPARK_DATA_ROOT") or os.path.join(
        tempfile.gettempdir(), "tsspark_plane"
    )


def dataset_dir(spec: DatasetSpec, root: Optional[str] = None) -> str:
    return os.path.join(root or default_root(), spec.cache_key())


def shard_ranges(spec: DatasetSpec) -> List[Tuple[int, int]]:
    return [
        (lo, min(lo + spec.shard_rows, spec.n_series))
        for lo in range(0, spec.n_series, spec.shard_rows)
    ]


def generate_rows(spec: DatasetSpec, lo: int, hi: int) -> SeriesBatch:
    """Canonical in-memory generation of rows [lo, hi) — what the cache
    must match bitwise (after the float32/nan_to_num disk conversion)."""
    if spec.generator.startswith("import:"):
        raise ValueError(
            "imported datasets have no generator; read the cache"
        )
    return GENERATORS[spec.generator](
        lo, hi, spec.n_timesteps, spec.seed
    )


def series_ids(spec: DatasetSpec, lo: int = 0,
               hi: Optional[int] = None) -> np.ndarray:
    return datasets.dataset_ids(
        spec.generator, lo, spec.n_series if hi is None else hi
    )


# ---------------------------------------------------------------------------
# disk conversion
# ---------------------------------------------------------------------------


def batch_columns(batch: SeriesBatch) -> Dict[str, np.ndarray]:
    """SeriesBatch -> the float32 column dict the cache stores (NaN
    holes become zeros; the mask carries observedness — the exact
    conversion bench.py's old private cache applied)."""
    cols = {
        "y": np.nan_to_num(np.asarray(batch.y)).astype(np.float32),
        "mask": np.asarray(batch.mask, np.float32),
    }
    if batch.regressors is not None:
        cols["reg"] = np.asarray(batch.regressors, np.float32)
    if batch.cap is not None:
        cols["cap"] = np.asarray(batch.cap, np.float32)
    return cols


def _shard_crcs(cols: Dict[str, np.ndarray]) -> Dict[str, int]:
    return {
        k: zlib.crc32(np.ascontiguousarray(v).tobytes())
        for k, v in cols.items()
    }


def _sentinel_path(dset_dir: str, lo: int, hi: int) -> str:
    return os.path.join(dset_dir, f"shardok_{lo:09d}_{hi:09d}.json")


# ---------------------------------------------------------------------------
# writers
# ---------------------------------------------------------------------------


def _column_shapes(spec: DatasetSpec,
                   fields: Sequence[str]) -> Dict[str, Tuple[int, ...]]:
    n, t = spec.n_series, spec.n_timesteps
    shapes: Dict[str, Tuple[int, ...]] = {}
    for f in fields:
        if f == "reg":
            # Regressor count comes from a 1-row probe at create time
            # and is recorded in spec.json; see create_columns.
            continue
        shapes[f] = (n, t)
    return shapes


def _prealloc_column(path: str, shape: Tuple[int, ...]) -> None:
    """Preallocate one column file WITHOUT ever clobbering an existing
    one: the memmap is built under a dot-temp name and published with
    ``os.link`` (atomic create-if-absent — it FAILS when the path
    exists, unlike rename).  Two cold producers racing the same spec
    then cannot truncate rows — or orphan sentinels — the other has
    already landed; the loser simply adopts the winner's file."""
    if os.path.exists(path):
        return
    d, base = os.path.split(os.path.abspath(path))
    tmp = os.path.join(d, f".{base}.tmp.{os.getpid()}")
    mm = np.lib.format.open_memmap(tmp, mode="w+", dtype=np.float32,
                                   shape=shape)
    del mm
    try:
        os.link(tmp, path)
    except FileExistsError:
        pass  # a racer published first; keep theirs (rows may be landed)
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass


def read_spec(dset_dir: str) -> Optional[Dict]:
    """The dataset's identity record, or None when ``dset_dir`` is not
    a plane dataset (e.g. a plain ``orchestrate.spill_data`` dir)."""
    try:
        with open(os.path.join(dset_dir, _SPEC_FILE)) as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


def create_columns(spec: DatasetSpec, root: Optional[str] = None) -> str:
    """Create (or adopt) the dataset dir: write ``spec.json`` + the
    shared calendar atomically and preallocate the column memmaps.

    Idempotent and race-safe: the column bytes are deterministic, so two
    creators racing the same spec produce identical files; preallocation
    itself is NOT atomic but no reader ever touches column rows before
    their shard sentinel exists (the sentinel, not the column file, is
    the unit of visibility)."""
    dset_dir = dataset_dir(spec, root)
    os.makedirs(dset_dir, exist_ok=True)
    record = read_spec(dset_dir)
    if record is not None:
        return dset_dir
    if spec.generator.startswith("import:"):
        raise ValueError("import_batch owns imported dataset creation")
    # Field/regressor discovery probes a TINY grid (fields and reg count
    # are per-generator constants, independent of T); the real calendar
    # comes from the closed-form grid so creation never generates a
    # full seed block on a consumer's blocked path.
    probe = generate_rows(
        dataclasses.replace(spec, n_timesteps=min(spec.n_timesteps, 8)),
        0, 1,
    )
    cols = batch_columns(probe)
    fields = sorted(cols)
    atomic_write(
        os.path.join(dset_dir, "ds.npy"),
        lambda fh: np.save(fh, datasets.dataset_calendar(
            spec.generator, spec.n_timesteps)),
    )
    for f in fields:
        shape = ((spec.n_series, spec.n_timesteps)
                 + cols[f].shape[2:])
        _prealloc_column(os.path.join(dset_dir, f"{f}.npy"), shape)
    record = dict(spec.to_dict(), fields=fields,
                  fingerprint=dataset_fingerprint(),
                  plane_version=PLANE_VERSION,
                  reg_names=list(probe.regressor_names))
    atomic_write(
        os.path.join(dset_dir, _SPEC_FILE),
        lambda fh: json.dump(record, fh, indent=1), mode="w",
    )
    return dset_dir


def write_shard(spec: DatasetSpec, shard_index: int,
                root: Optional[str] = None) -> Tuple[int, int]:
    """Generate and land one shard: fill the column memmap rows, flush,
    then publish the sentinel (atomic, CRCs inside) that makes the rows
    visible.  Emits a ``datagen.shard`` span + shard counters when a
    trace is bound.  Returns the (lo, hi) landed."""
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS

    t0 = time.time()
    dset_dir = create_columns(spec, root)
    lo, hi = shard_ranges(spec)[shard_index]
    batch = generate_rows(spec, lo, hi)
    cols = batch_columns(batch)
    for f, rows in cols.items():
        mm = np.lib.format.open_memmap(
            os.path.join(dset_dir, f"{f}.npy"), mode="r+"
        )
        mm[lo:hi] = rows
        mm.flush()
        del mm
    sentinel = {
        "lo": lo, "hi": hi, "unix": round(time.time(), 3),
        "crc": _shard_crcs(cols), "pid": os.getpid(),
    }
    atomic_write(
        _sentinel_path(dset_dir, lo, hi),
        lambda fh: json.dump(sentinel, fh), mode="w",
    )
    dur = time.time() - t0
    if obs.active():
        obs.record("datagen.shard", t0, dur, lo=lo, hi=hi,
                   generator=spec.generator, rows=hi - lo)
        METRICS.counter("tsspark_datagen_shards_total").inc()
        METRICS.counter("tsspark_datagen_rows_total").inc(hi - lo)
        METRICS.histogram("tsspark_datagen_shard_seconds").observe(dur)
    return lo, hi


def finalize(spec: DatasetSpec, root: Optional[str] = None) -> str:
    """Write the manifest once sentinel coverage is complete (atomic,
    LAST — the manifest is the warm-cache hit marker, so it must never
    exist before every shard it certifies)."""
    dset_dir = dataset_dir(spec, root)
    missing = missing_shards(spec, root)
    if missing:
        raise RuntimeError(
            f"cannot finalize {dset_dir}: shards {missing} not landed"
        )
    record = dict(read_spec(dset_dir) or spec.to_dict(),
                  complete=True, unix=round(time.time(), 3),
                  shards=[list(r) for r in shard_ranges(spec)])
    atomic_write(
        os.path.join(dset_dir, _MANIFEST_FILE),
        lambda fh: json.dump(record, fh, indent=1), mode="w",
    )
    return dset_dir


def import_batch(batch: SeriesBatch, name: str,
                 root: Optional[str] = None,
                 shard_rows: int = DEFAULT_SHARD_ROWS) -> str:
    """Bring an externally-loaded batch (e.g. the real M5 CSVs via
    ``data.loaders``) under the same manifest: columns + sentinels +
    manifest, keyed ``import:<name>`` with a content hash so a changed
    file set never aliases a stale cache."""
    cols = batch_columns(batch)
    content = hashlib.md5()
    for f in sorted(cols):
        content.update(np.ascontiguousarray(cols[f]).tobytes())
    n, t = cols["y"].shape
    spec = DatasetSpec(
        generator=f"import:{name}_{content.hexdigest()[:8]}",
        n_series=n, n_timesteps=t, seed=0, shard_rows=shard_rows,
    )
    dset_dir = dataset_dir(spec, root)
    if is_complete(dset_dir):
        return dset_dir
    os.makedirs(dset_dir, exist_ok=True)
    atomic_write(
        os.path.join(dset_dir, "ds.npy"),
        lambda fh: np.save(fh, np.asarray(batch.ds, np.float64)),
    )
    fields = sorted(cols)
    for f in fields:
        path = os.path.join(dset_dir, f"{f}.npy")
        _prealloc_column(path, cols[f].shape)
        mm = np.lib.format.open_memmap(path, mode="r+")
        mm[:] = cols[f]
        mm.flush()
        del mm
    record = dict(spec.to_dict(), fields=fields,
                  fingerprint=dataset_fingerprint(),
                  plane_version=PLANE_VERSION,
                  reg_names=list(batch.regressor_names),
                  series_ids=[str(s) for s in batch.series_ids])
    atomic_write(
        os.path.join(dset_dir, _SPEC_FILE),
        lambda fh: json.dump(record, fh, indent=1), mode="w",
    )
    for lo, hi in shard_ranges(spec):
        sentinel = {
            "lo": lo, "hi": hi, "unix": round(time.time(), 3),
            "crc": _shard_crcs({f: cols[f][lo:hi] for f in fields}),
            "pid": os.getpid(),
        }
        atomic_write(
            _sentinel_path(dset_dir, lo, hi),
            lambda fh, s=sentinel: json.dump(s, fh), mode="w",
        )
    return finalize(spec, root)


# ---------------------------------------------------------------------------
# readers / coverage
# ---------------------------------------------------------------------------


def is_complete(dset_dir: str) -> bool:
    """Warm-cache hit test: a readable manifest marked complete."""
    try:
        with open(os.path.join(dset_dir, _MANIFEST_FILE)) as fh:
            return bool(json.load(fh).get("complete"))
    except (OSError, ValueError):
        return False


def landed_ranges(dset_dir: str) -> List[Tuple[int, int]]:
    """Merged row coverage of all landed shard sentinels (a torn
    sentinel — its writer died inside atomic_write, which cannot happen,
    but a hand-corrupted one can — reads as absent)."""
    spans = []
    for p in glob.glob(os.path.join(dset_dir, "shardok_*.json")):
        stem = os.path.basename(p)[len("shardok_"):-len(".json")]
        try:
            lo, hi = (int(x) for x in stem.split("_"))
        except ValueError:
            continue
        spans.append((lo, hi))
    merged: List[Tuple[int, int]] = []
    for lo, hi in sorted(spans):
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    return merged


def covers(ranges: Sequence[Tuple[int, int]], lo: int, hi: int) -> bool:
    """True when [lo, hi) lies inside the merged coverage."""
    for r_lo, r_hi in ranges:
        if r_lo <= lo and hi <= r_hi:
            return True
    return False


def ready_coverage(data_dir: str,
                   n_series: Optional[int] = None
                   ) -> Optional[List[Tuple[int, int]]]:
    """The row ranges a consumer may read RIGHT NOW, or None when no
    gating applies (a plain spill dir, or a complete dataset): the fit
    worker's claim filter during overlapped ingestion."""
    if read_spec(data_dir) is None:
        return None  # not a plane dataset: everything is ready
    if is_complete(data_dir):
        return None
    ranges = landed_ranges(data_dir)
    if n_series is not None:
        ranges = [(lo, min(hi, n_series)) for lo, hi in ranges
                  if lo < n_series]
    return ranges


def ingest_pending(data_dir: str, n_series: Optional[int] = None) -> bool:
    """True while a plane dataset's sentinel coverage is still
    incomplete (the consumer should wait — or self-produce — rather
    than give up)."""
    spec_rec = read_spec(data_dir)
    if spec_rec is None or is_complete(data_dir):
        return False
    total = spec_rec.get("n_series", 0)
    if n_series is not None:
        total = min(total, n_series)
    merged = landed_ranges(data_dir)
    covered = sum(min(hi, total) - lo for lo, hi in merged if lo < total)
    return covered < total


def missing_shards(spec: DatasetSpec,
                   root: Optional[str] = None) -> List[int]:
    dset_dir = dataset_dir(spec, root)
    landed = landed_ranges(dset_dir)
    return [
        i for i, (lo, hi) in enumerate(shard_ranges(spec))
        if not covers(landed, lo, hi)
    ]


def produce_next_missing(data_dir: str) -> bool:
    """Self-healing consumer path: generate + land the first missing
    shard inline (deterministic — identical bytes to whatever the dead
    ingest driver would have written).  Returns False when nothing is
    missing or the dir is not a generated plane dataset."""
    rec = read_spec(data_dir)
    if rec is None or str(rec.get("generator", "")).startswith("import:"):
        return False
    spec = DatasetSpec.from_dict(rec)
    root = os.path.dirname(os.path.abspath(data_dir))
    if os.path.abspath(dataset_dir(spec, root)) \
            != os.path.abspath(data_dir):
        # The dir was keyed under a different fingerprint (source edited
        # since creation): self-producing would land shards in a NEW dir
        # this consumer never reads — decline instead.
        return False
    missing = missing_shards(spec, root=root)
    if not missing:
        return False
    write_shard(spec, missing[0], root=root)
    return True


def verify_shard(dset_dir: str, lo: int, hi: int) -> bool:
    """Deep integrity check of one landed shard: recompute the column
    CRCs over the memmap rows and compare with the sentinel's.  False
    means the shard is torn/corrupt (reject it; :func:`repair` re-lands
    it)."""
    try:
        with open(_sentinel_path(dset_dir, lo, hi)) as fh:
            sentinel = json.load(fh)
    except (OSError, ValueError):
        return False
    crcs = sentinel.get("crc") or {}
    for f, want in crcs.items():
        path = os.path.join(dset_dir, f"{f}.npy")
        try:
            mm = np.load(path, mmap_mode="r")
        except (OSError, ValueError):
            return False
        got = zlib.crc32(np.ascontiguousarray(mm[lo:hi]).tobytes())
        del mm
        if got != int(want):
            return False
    return True


def repair(spec: DatasetSpec, root: Optional[str] = None,
           deep: bool = True) -> List[Tuple[int, int]]:
    """Re-land every missing or (with ``deep``) CRC-failing shard and
    drop a stale manifest first so a corrupt dataset can never keep its
    warm-hit marker.  Returns the ranges rewritten."""
    dset_dir = dataset_dir(spec, root)
    bad: List[Tuple[int, int]] = []
    ranges = shard_ranges(spec)
    for i, (lo, hi) in enumerate(ranges):
        landed = covers(landed_ranges(dset_dir), lo, hi)
        if landed and (not deep or verify_shard(dset_dir, lo, hi)):
            continue
        bad.append((lo, hi))
        try:
            os.remove(os.path.join(dset_dir, _MANIFEST_FILE))
        except OSError:
            pass
        write_shard(spec, i, root)
    if bad and not missing_shards(spec, root):
        finalize(spec, root)
    return bad


def open_batch(dset_dir: str, mmap: bool = True) -> SeriesBatch:
    """Read a COMPLETE dataset as a SeriesBatch of memmap columns (the
    warm path: zero generation, zero copies until a consumer slices)."""
    if not is_complete(dset_dir):
        raise FileNotFoundError(
            f"{dset_dir} has no complete plane manifest (cold cache? "
            "run ensure()/ingest first)"
        )
    rec = read_spec(dset_dir) or {}
    mode = "r" if mmap else None
    load = lambda f: np.load(os.path.join(dset_dir, f"{f}.npy"),
                             mmap_mode=mode)
    fields = rec.get("fields") or ["mask", "y"]
    ids = rec.get("series_ids")
    if ids is None:
        ids = datasets.dataset_ids(
            rec.get("generator", "m5"), 0, int(rec.get("n_series", 0))
        )
    else:
        ids = np.asarray(ids)
    return SeriesBatch(
        ds=np.load(os.path.join(dset_dir, "ds.npy")),
        y=load("y"), mask=load("mask"), series_ids=ids,
        regressors=load("reg") if "reg" in fields else None,
        cap=load("cap") if "cap" in fields else None,
        regressor_names=tuple(rec.get("reg_names") or ()),
    )


#: A dataset untouched this long is reaped by the cold-path sweep: the
#: datagen fingerprint is part of every key, so each data-package edit
#: strands the previous keys' full-size dirs forever otherwise.
STALE_DATASET_S = 7 * 24 * 3600.0


def sweep_stale_datasets(root: Optional[str] = None,
                         max_age_s: float = STALE_DATASET_S) -> int:
    """Remove dataset dirs whose NEWEST file mtime is older than
    ``max_age_s`` (same age-gated pattern as bench's scratch reaper: a
    dir any producer or landing shard touched recently is live).  Runs
    on the cold ingest path only — warm hits never pay the scan.
    Unlinking under a concurrent reader is safe: its mmap keeps the
    bytes until unmapped.  Returns the count removed."""
    import shutil

    root = root or default_root()
    removed = 0
    try:
        entries = [os.path.join(root, n) for n in os.listdir(root)]
    except OSError:
        return 0
    now = time.time()
    for d in entries:
        if not os.path.isdir(d):
            continue
        try:
            newest = max(
                (os.path.getmtime(p) for p in
                 glob.glob(os.path.join(d, "**"), recursive=True)),
                default=os.path.getmtime(d),
            )
        except OSError:
            continue
        if now - newest > max_age_s:
            shutil.rmtree(d, ignore_errors=True)
            removed += 1
    return removed


def ensure(spec: DatasetSpec, root: Optional[str] = None,
           processes: int = 0) -> str:
    """The front door: return the dataset dir, ingesting first when the
    cache misses (``processes`` > 1 fans shard generation out to a
    process pool via :mod:`tsspark_tpu.data.ingest`).  Emits cache
    hit/miss counters into the obs registry."""
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS

    dset_dir = dataset_dir(spec, root)
    if is_complete(dset_dir):
        METRICS.counter("tsspark_datagen_cache_hits_total").inc()
        return dset_dir
    METRICS.counter("tsspark_datagen_cache_misses_total").inc()
    from tsspark_tpu.data import ingest

    return ingest.run_ingest(spec, root=root, processes=processes)
