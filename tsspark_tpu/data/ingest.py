"""Streaming dataset ingestion: produce plane shards ahead of consumers.

``run_ingest`` fills a :mod:`tsspark_tpu.data.plane` dataset shard by
shard — serially or on a process pool — landing each shard's sentinel
the moment its rows are durable, so consumers gated on
``plane.ready_coverage`` (the orchestrate fit workers) start fitting
while later shards are still generating.  ``IngestDriver`` runs the
whole thing as a detached background process for callers (bench.py)
that must stay on their own critical path: generation overlaps fitting
instead of preceding it.

JAX-free by construction (pure numpy): a wedged accelerator runtime can
never block data production.

CLI::

    python -m tsspark_tpu.data.ingest --generator m5 --series 30490 \
        --timesteps 1941 [--seed 2] [--shard-rows 1024] [--root DIR] \
        [--processes N]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional

from tsspark_tpu.data import plane
from tsspark_tpu.obs import context as obs
from tsspark_tpu.io import atomic_write

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
))

INGEST_REPORT = "ingest_report.json"


def _pool_init(env_blob: Optional[str]) -> None:
    """Process-pool worker init: adopt the parent's trace so every
    ``datagen.shard`` span joins the run (fork already inherits the
    binding; this also covers spawn-start platforms)."""
    if env_blob:
        os.environ[obs.ENV_VAR] = env_blob
        obs.adopt_env()


def _shard_job(spec_dict: Dict, root: Optional[str], index: int) -> float:
    t0 = time.time()
    plane.write_shard(plane.DatasetSpec.from_dict(spec_dict), index,
                      root=root)
    return time.time() - t0


def run_ingest(spec: plane.DatasetSpec, root: Optional[str] = None,
               processes: int = 0) -> str:
    """Ingest every still-missing shard of ``spec`` and finalize the
    manifest.  Resumable: a previous crashed ingest's landed shards are
    kept (sentinel-gated), only the holes are produced.  Returns the
    dataset dir and leaves an ``ingest_report.json`` beside the data
    (overlap accounting for BENCH extras)."""
    t0 = time.time()
    plane.sweep_stale_datasets(root)  # cold path: reap superseded keys
    dset_dir = plane.create_columns(spec, root)
    missing = plane.missing_shards(spec, root)
    span = obs.open_span("datagen.ingest", generator=spec.generator,
                         n_series=spec.n_series, shards=len(missing))
    t_first = t_last = None
    if len(missing) > 1 and processes and processes > 1:
        import multiprocessing
        from concurrent.futures import ProcessPoolExecutor, as_completed

        env_blob = None
        if obs.active():
            env: Dict[str, str] = {}
            obs.inject_env(env, parent_id=span)
            env_blob = env.get(obs.ENV_VAR)
        # fork-ing a JAX-loaded process can deadlock on XLA's threads;
        # the IngestDriver subprocess is numpy-only so its pool forks
        # safely, but an in-process caller that already imported jax
        # (serve loadgen, tests) gets spawn-start workers instead —
        # _pool_init re-adopts the trace either way.
        ctx = multiprocessing.get_context(
            "spawn" if "jax" in sys.modules else None
        )
        with ProcessPoolExecutor(
            max_workers=min(processes, len(missing)),
            initializer=_pool_init, initargs=(env_blob,),
            mp_context=ctx,
        ) as pool:
            futs = [
                pool.submit(_shard_job, spec.to_dict(), root, i)
                for i in missing
            ]
            # Completion order, not submission order: shard 0 being the
            # slowest must not overstate first_shard_s (the overlap
            # accounting BENCH folds into its extras).
            for f in as_completed(futs):
                f.result()
                now = time.time()
                t_first = t_first or now
                t_last = now
    else:
        for i in missing:
            plane.write_shard(spec, i, root=root)
            now = time.time()
            t_first = t_first or now
            t_last = now
    plane.finalize(spec, root)
    obs.close_span(span, "datagen.ingest", t0, shards=len(missing))
    wall = time.time() - t0
    report = {
        "generator": spec.generator, "n_series": spec.n_series,
        "n_timesteps": spec.n_timesteps, "shards_produced": len(missing),
        "shards_total": len(plane.shard_ranges(spec)),
        "processes": int(processes or 1),
        "wall_s": round(wall, 3),
        "first_shard_s": round((t_first - t0), 3) if t_first else 0.0,
        "last_shard_s": round((t_last - t0), 3) if t_last else 0.0,
        "unix": round(time.time(), 3),
    }
    atomic_write(
        os.path.join(dset_dir, INGEST_REPORT),
        lambda fh: json.dump(report, fh, indent=1), mode="w",
    )
    return dset_dir


def read_ingest_report(dset_dir: str) -> Optional[Dict]:
    try:
        with open(os.path.join(dset_dir, INGEST_REPORT)) as fh:
            d = json.load(fh)
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


class IngestDriver:
    """A background ingest subprocess (the overlap producer).

    The child is plain ``python -m tsspark_tpu.data.ingest``: it
    survives the spawner's JAX state entirely (numpy-only) and its
    ``datagen.shard`` spans join the spawner's trace through the
    injected ``TSSPARK_TRACE`` env.  The caller consumes
    ``plane.ready_coverage`` while this runs, and must ``kill()`` it
    from signal handlers like any other worker child."""

    def __init__(self, spec: plane.DatasetSpec, proc: subprocess.Popen,
                 root: Optional[str]):
        self.spec = spec
        self.proc = proc
        self.dataset_dir = plane.dataset_dir(spec, root)

    @classmethod
    def start(cls, spec: plane.DatasetSpec, root: Optional[str] = None,
              processes: Optional[int] = None,
              log_stream=None) -> "IngestDriver":
        if processes is None:
            processes = max(1, (os.cpu_count() or 2) - 1)
        # Columns are preallocated HERE, synchronously, so a consumer
        # spawned the instant this returns always finds a valid plane
        # dir (spec.json + calendar + column files) — only shard
        # coverage, never dir existence, gates it.  Cheap: a 1-row
        # probe plus sparse-file preallocation.
        plane.create_columns(spec, root)
        env = dict(os.environ)
        parts = [_REPO_ROOT] + (
            [env["PYTHONPATH"]] if env.get("PYTHONPATH") else []
        )
        env["PYTHONPATH"] = os.pathsep.join(parts)
        obs.inject_env(env)
        cmd = [
            sys.executable, "-m", "tsspark_tpu.data.ingest",
            "--generator", spec.generator,
            "--series", str(spec.n_series),
            "--timesteps", str(spec.n_timesteps),
            "--seed", str(spec.seed),
            "--shard-rows", str(spec.shard_rows),
            "--processes", str(processes),
        ]
        if root:
            cmd += ["--root", root]
        proc = subprocess.Popen(cmd, stdout=log_stream or sys.stderr,
                                stderr=log_stream or sys.stderr, env=env)
        return cls(spec, proc, root)

    def alive(self) -> bool:
        return self.proc.poll() is None

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def kill(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="produce plane dataset shards (numpy-only)"
    )
    ap.add_argument("--generator", required=True)
    ap.add_argument("--series", type=int, required=True)
    ap.add_argument("--timesteps", type=int, required=True)
    ap.add_argument("--seed", type=int, default=2)
    ap.add_argument("--shard-rows", type=int,
                    default=plane.DEFAULT_SHARD_ROWS)
    ap.add_argument("--root", default=None)
    ap.add_argument("--processes", type=int, default=1)
    args = ap.parse_args(argv)
    obs.adopt_env()
    spec = plane.DatasetSpec(
        generator=args.generator, n_series=args.series,
        n_timesteps=args.timesteps, seed=args.seed,
        shard_rows=args.shard_rows,
    )
    dset_dir = run_ingest(spec, root=args.root, processes=args.processes)
    print(f"[ingest] {spec.cache_key()} complete -> {dset_dir}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
