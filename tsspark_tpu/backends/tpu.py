"""TPU backend: the batched JAX fit, chunked over series to bound HBM.

One jitted program fits a fixed-size chunk of series; batches larger than
``chunk_size`` stream through it (same shapes -> one compile, reused).  The
last chunk is padded with inert dummy series (mask all-zero) so every chunk
hits the same compiled executable — the batched analog of the reference's
fixed-size Spark partitions (BASELINE.json:5).

The name says "tpu" to match the reference's ``backend="tpu"`` API; the same
code runs on any JAX backend (tests exercise it on the forced-CPU mesh).
"""

from __future__ import annotations

import contextlib
import dataclasses
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tsspark_tpu.backends.registry import ForecastBackend, register_backend
from tsspark_tpu.resilience import faults
from tsspark_tpu.resilience.report import ResilienceWarning, add_warning
from tsspark_tpu.models.prophet import predict as predict_mod
from tsspark_tpu.models.prophet.design import (
    _indicator_reg_cols,
    packable_batch,
)
from tsspark_tpu.models.prophet.model import (
    FitState,
    KEEP_BEST_MARGIN,
    ProphetModel,
    select_better_state,
)


# One-time flag for the resilient-gate semantic-switch warning: every
# eligible fit after the first stays quiet (the note still rides each
# returned state's resilience report).
_RESILIENT_GATE_WARNED = False

# One-time flag for the opposite edge of the same gate: resilient=True
# was requested but the batch is INELIGIBLE for the worker path, so the
# fit silently loses process isolation / crash resume.  Announced once,
# naming the failed eligibility check(s), so a user who asked for
# resilience learns which input property cost them it.
_RESILIENT_FALLBACK_WARNED = False


def _resilient_ineligibility(dyn_used, init, conditions, mesh, packable):
    """Human-readable list of the eligibility checks a resilient=True fit
    failed (empty = eligible for the worker path)."""
    failed = []
    if dyn_used:
        failed.append("traced phase controls (max_iters/gn_precond/"
                      "use_init dynamic args) were passed")
    if init is not None:
        failed.append("an explicit warm start (init=) was passed")
    if conditions is not None:
        failed.append("conditional-seasonality data (conditions=) was "
                      "passed")
    if mesh is not None:
        failed.append("the backend is mesh-sharded (mesh=)")
    if not packable:
        failed.append("the batch is not packable (needs a shared 1-D ds "
                      "grid and an exact 0/1 mask)")
    return failed


def _mesh_series_axis(mesh, shard_config=None) -> str:
    """The mesh axis that shards the series dimension: the config's
    declared name when given; else conventional names win over position —
    "series" wherever it appears, with only "time" named the first
    non-"time" axis, otherwise the first axis (ADVICE r4)."""
    if shard_config is not None:
        return shard_config.series_axis
    names = mesh.axis_names
    if "series" in names:
        return "series"
    if "time" in names and len(names) > 1:
        return next(n for n in names if n != "time")
    return names[0]


def _pad_batch(arr, b_pad):
    """Host-side (numpy) zero-padding along the batch axis.

    The whole pre-fit pipeline stays on host numpy: device arrays here
    would mean shipping the full batch over the link just to slice it
    back per chunk (and the padding .at[].set ops would each dispatch)."""
    if arr is None:
        return None
    arr = np.asarray(arr)
    if arr.shape[0] == b_pad:
        return arr
    pad = [(0, b_pad - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def _slice_state(state: FitState, lo: int, hi: int) -> FitState:
    return jax.tree.map(lambda a: a[lo:hi], state)


def _slice_repeat_pad(a, lo: int, hi: int, c: int):
    """Batch-axis slice [lo:hi], padded to exactly ``c`` rows by repeating
    the first row (valid dummy data whose outputs are discarded) so every
    chunk hits one compiled shape.  Zero-padding (fit's policy, _pad_batch)
    is wrong here: there is no mask input on the predict path to make
    zero rows inert."""
    if a is None:
        return None
    a = np.asarray(a)[lo:hi]
    if hi - lo < c:
        a = np.concatenate([a, np.repeat(a[:1], c - (hi - lo), axis=0)])
    return a


def _concat_states(states) -> FitState:
    # Host numpy leaves (ScalingMeta, float64) concatenate as numpy;
    # jnp.concatenate would silently downcast them to f32.
    def cat(*xs):
        if isinstance(xs[0], np.ndarray):
            return np.concatenate(xs, axis=0)
        return jnp.concatenate(xs, axis=0)

    return jax.tree.map(cat, *states)


@register_backend
class TpuBackend(ForecastBackend):
    name = "tpu"

    def __init__(self, *args, chunk_size: int = 8192,
                 iter_segment: Optional[int] = None, on_segment=None,
                 length_buckets: Optional[int] = None,
                 rescue: bool = True,
                 mesh=None, shard_config=None,
                 resilient: bool = False, resilient_opts=None,
                 compact: bool = True, perf=None,
                 **kwargs):
        """chunk_size bounds series per program; iter_segment bounds solver
        iterations per program.

        ``iter_segment`` splits one long L-BFGS solve into several short
        XLA executions with the full solver state carried across, so the
        trajectory is identical to one long program.  Buys bounded
        per-dispatch execution time — needed on runtimes that kill
        long-running programs (the tunneled dev chip here), and useful for
        checkpoint/preemption granularity generally.

        ``length_buckets``: ragged-length batches (the M4-Hourly regime,
        SURVEY.md §7 hard part c) are padded to the full calendar grid;
        device work then scales with the LONGEST series.  When a shared
        1-D grid is used, ``fit`` groups series by observed window into up
        to this many buckets and slices each bucket's time axis to its own
        (128-aligned) window, so short series stop paying for the longest
        one.  None (default) = auto: up to 3 buckets, applied only when it
        saves >= 20% of padded cells; 1 disables.  Masked cells contribute
        exact zeros to every reduction, so bucketing changes results only
        at f32 reduction-order level.

        ``rescue``: a series can exit the lockstep solver STUCK rather
        than solved — status FLOOR (no f32-resolvable progress) or STALLED
        (no acceptable step) prove only that the plain metric ran out of
        resolvable descent, and on the M5 eval config the whole
        holdout-parity tail versus the scipy oracle was exactly such
        series (round-3 verdict, Weak #3).  When enabled, ``fit`` follows
        the main solve with a compacted GN-diagonal multi-start pass over
        those suspects (warm-started from their stuck point AND fresh from
        the ridge init) and keeps each series' best loss, original
        included — so the pass can only improve.  Disabled internally for
        phase-1 / straggler sub-backends (fit_twophase owns that flow).

        ``mesh``: a ``jax.sharding.Mesh`` routes every chunk's solve
        through the sharded program (parallel.sharding.fit_sharded —
        series-axis data parallelism plus optional time-axis sequence
        parallelism per ``shard_config``) instead of the single-device
        program.  This is the multi-chip path: collect -> shard -> fit ->
        scatter (BASELINE.json:5) behind the same ``fit`` signature.
        Incompatible with ``iter_segment`` (the sharded solve runs as one
        program; segmenting it is not implemented — raise rather than
        silently ignore the bounded-dispatch contract).  ``on_segment``
        still fires once per chunk solve.
        ``shard_config``: a ShardingConfig; defaults to axis names taken
        from the mesh (series first, optional time second).

        ``resilient``: route eligible fits (shared 1-D grid, no warm
        start / conditions / traced controls, no mesh) through
        ``tsspark_tpu.orchestrate.fit_resilient`` — process-isolated
        chunk workers with crash retry, stall watchdog, accelerator
        probing, and resumable per-chunk results; the elastic-recovery
        story Spark gave the reference for free (SURVEY.md §2.5).
        Semantics are ``fit_twophase``'s (speed-first: no rescue pass).
        Ineligible inputs fall back to the in-process fit.
        ``resilient_opts`` forwards keywords to ``fit_resilient``
        (scratch_dir, budget_s, phase1_iters, ...).

        ``compact``: on segmented solves (``iter_segment``), shrink the
        lockstep batch to the unconverged set between segments — the
        convergence-compacting scheduler (models.prophet.model.
        _run_segments_compacted; per-series results are bitwise
        identical, per-iteration cost tracks the live set).  Widths walk
        the same pow-2/32-floor ladder as the chunk padding, so shrunk
        widths reuse compiled programs.  No-op on unsegmented solves
        (one fused program has no between-segment boundary to compact
        at) — which today includes every mesh solve (mesh excludes
        iter_segment above); the width policy (sharding.compacted_width)
        still accepts a series-shard multiple so a future segmented
        sharded program composes without new padding rules.

        ``perf`` (tsspark_tpu.perf.PerfRecorder): per-dispatch telemetry
        accumulated across every chunk/segment this backend dispatches;
        the cumulative report is attached to each returned FitState as
        ``state.perf`` (perf.get_perf).  Telemetry blocks per dispatch
        to time it, so leave it None on latency-critical pipelines."""
        super().__init__(*args, **kwargs)
        if mesh is not None and iter_segment:
            raise ValueError(
                "TpuBackend(mesh=...) does not support iter_segment: the "
                "sharded solve runs as one XLA program"
            )
        self.chunk_size = chunk_size
        self.iter_segment = iter_segment
        self.on_segment = on_segment  # liveness hook, fires per dispatch
        self.length_buckets = length_buckets
        self.rescue = rescue
        self.mesh = mesh
        self.shard_config = shard_config
        self.resilient = resilient
        self.resilient_opts = dict(resilient_opts or {})
        self.compact = compact
        self.perf = perf
        self._model = ProphetModel(self.config, self.solver_config)

    def _compact_multiple(self) -> int:
        """Series-axis shard count a compacted width must divide into
        (1 off-mesh) — the ``multiple`` the width policy
        (``parallel.sharding.compacted_width``) pads up to.

        Today this is 1 on every path that actually compacts: the mesh
        and ``iter_segment`` are mutually exclusive (see __init__), and
        compaction only runs on segmented solves — so the mesh branch is
        consulted only by tests and by a future segmented-mesh path.
        The resolution is shared with _fit_sharded_chunk
        (``_mesh_series_axis``) so the two can never disagree."""
        if self.mesh is None:
            return 1
        return int(self.mesh.shape[_mesh_series_axis(
            self.mesh, self.shard_config
        )])

    def _plan_length_buckets(self, y, mask):
        """Bucket series by observed time window.

        Returns a list of (row_idx, lo_t, hi_t) covering every row exactly
        once, or None when bucketing is off / not worth it.  Buckets are
        built from the sorted window-span order, their windows are aligned
        up to 128 columns (coarse compile shapes, reusable across calls),
        near-equal buckets are merged, and the plan is kept only if it
        saves >= 20% of the (B, T) cells the unbucketed fit would pay for.
        """
        if self.length_buckets == 1:
            return None
        b, t_len = y.shape
        if b < 32 or t_len < 256:
            return None  # too small for the extra compile shapes to pay
        m = (np.asarray(mask) > 0) if mask is not None else np.isfinite(y)
        any_obs = m.any(axis=1)
        first = np.where(any_obs, m.argmax(axis=1), 0)
        last = np.where(
            any_obs, t_len - 1 - m[:, ::-1].argmax(axis=1), -1
        )
        span = last - first + 1  # 0 for all-masked rows
        k = self.length_buckets or 3
        order = np.argsort(span, kind="stable")
        cuts = [round(i * b / k) for i in range(k + 1)]
        plan = []
        for i in range(k):
            idx = order[cuts[i]:cuts[i + 1]]
            if idx.size == 0:
                continue
            sel = idx[any_obs[idx]]
            lo = int(first[sel].min()) if sel.size else 0
            hi = int(last[sel].max()) + 1 if sel.size else 1
            # Align the window length up to 128 columns, preferring to
            # extend toward lo (keeps hi, the "now" edge, stable for the
            # right-aligned M4 layout).
            length = min(t_len, -(-(hi - lo) // 128) * 128)
            lo = max(0, hi - length)
            hi = min(t_len, lo + length)
            if plan:
                prev_idx, prev_lo, prev_hi = plan[-1]
                union_lo = min(prev_lo, lo)
                union_hi = max(prev_hi, hi)
                # Merge only when the UNION window is barely bigger than
                # the SMALLER member — i.e. merging costs the smaller
                # bucket almost nothing.  Comparing against the larger
                # member would always merge nested windows (union ==
                # larger, erasing the smaller bucket's savings), and
                # similar LENGTHS alone are not enough either (two
                # equal-span buckets at disjoint calendar offsets would
                # union into a near-full-grid window).
                if union_hi - union_lo <= 1.15 * min(hi - lo,
                                                     prev_hi - prev_lo):
                    plan.pop()
                    idx = np.concatenate([prev_idx, idx])
                    lo, hi = union_lo, union_hi
            plan.append((idx, lo, hi))
        if len(plan) < 2:
            return None
        cost = sum(idx.size * (hi - lo) for idx, lo, hi in plan)
        if cost > 0.8 * b * t_len:
            return None
        return plan

    def fit(self, ds, y, mask=None, cap=None, floor=None, regressors=None,
            init=None, conditions=None, max_iters_dynamic=None,
            gn_precond_dynamic=None, use_init_dynamic=None,
            reg_u8_cols=None):
        faults.inject("backend_fit")
        dyn_used = any(
            v is not None for v in
            (max_iters_dynamic, gn_precond_dynamic, use_init_dynamic)
        )
        segmented = bool(
            self.iter_segment
            and self.iter_segment < self.solver_config.max_iters
        )
        if (self.resilient and not dyn_used and init is None
                and conditions is None and self.mesh is None
                and packable_batch(ds, mask)):
            from tsspark_tpu import orchestrate

            # The resilient route serves fit_twophase semantics: no
            # rescue pass, no length bucketing.  With rescue=True (the
            # backend default) or length_buckets set, two calls differing
            # only in eligibility (say, mask fractionality) would return
            # different-quality stuck-exit tails with no signal — so the
            # semantic switch is announced once and recorded on the
            # returned state (ADVICE r5).
            note = None
            if self.rescue or self.length_buckets not in (None, 1):
                note = (
                    "TpuBackend(resilient=True): this fit is served by "
                    "the two-phase worker path, which ignores "
                    f"rescue={self.rescue!r} and length_buckets="
                    f"{self.length_buckets!r}; ineligible batches "
                    "(fractional mask, 2-D ds, conditions, init) fall "
                    "back to the in-process fit WITH those features"
                )
                global _RESILIENT_GATE_WARNED
                if not _RESILIENT_GATE_WARNED:
                    _RESILIENT_GATE_WARNED = True
                    warnings.warn(note, ResilienceWarning, stacklevel=2)
            opts = dict(chunk=self.chunk_size)
            if self.iter_segment:
                opts["segment"] = self.iter_segment
            opts.update(self.resilient_opts)
            state = orchestrate.fit_resilient(
                self.config, self.solver_config, ds, y, mask=mask,
                regressors=regressors, cap=cap, floor=floor, **opts,
            )
            return add_warning(state, note) if note else state
        if self.resilient:
            # The gate declined this batch: the fit proceeds in-process,
            # WITHOUT process isolation or crash resume.  Silent fallback
            # would let a crash take the whole parent down exactly where
            # the user asked for resilience — announce once which
            # eligibility check failed.
            global _RESILIENT_FALLBACK_WARNED
            if not _RESILIENT_FALLBACK_WARNED:
                _RESILIENT_FALLBACK_WARNED = True
                # Diagnosis only inside the one-shot branch: the
                # packability re-check is an O(B*T) host mask scan that
                # a permanently-ineligible backend must not pay per fit.
                failed = _resilient_ineligibility(
                    dyn_used, init, conditions, self.mesh,
                    packable_batch(ds, mask),
                )
                warnings.warn(
                    "TpuBackend(resilient=True): this fit is INELIGIBLE "
                    "for the process-isolated worker path and falls back "
                    "to the in-process fit (no crash isolation/resume). "
                    "Failed eligibility check(s): "
                    + "; ".join(failed),
                    ResilienceWarning, stacklevel=2,
                )
        # Indicator-column split decided ONCE here so the main fit and the
        # rescue pass share it (it is a static argument of the jitted fit
        # and an O(B*T*R) host scan — see _fit_main).  Segmented solves
        # never reach the packed path, so skip it there; mesh-sharded
        # solves DO (fit_sharded_packed ships the packed form per shard).
        if (reg_u8_cols is None and regressors is not None
                and not segmented and np.asarray(ds).ndim == 1):
            reg_u8_cols = _indicator_reg_cols(np.asarray(regressors))
        # One full-batch out-of-span changepoint warning instead of a copy
        # per chunk with chunk-local counts (ADVICE r3).
        from tsspark_tpu.models.prophet.design import (
            changepoint_span_warning_suppressed,
            warn_out_of_span_changepoints,
        )

        warn_out_of_span_changepoints(self.config, ds, y, mask)
        with changepoint_span_warning_suppressed():
            state = self._fit_main(
                ds, y, mask=mask, cap=cap, floor=floor,
                regressors=regressors, init=init, conditions=conditions,
                max_iters_dynamic=max_iters_dynamic,
                gn_precond_dynamic=gn_precond_dynamic,
                use_init_dynamic=use_init_dynamic,
                reg_u8_cols=reg_u8_cols,
            )
        # No rescue under traced phase controls (fit_twophase owns that
        # flow via its straggler pass) or segmented solves (bounded
        # dispatches are the caller's priority there).
        if not self.rescue or dyn_used or segmented:
            return self._attach_perf(state)
        with changepoint_span_warning_suppressed():
            return self._attach_perf(self._rescue_pass(
                state, ds, y, mask, cap, floor, regressors, conditions,
                reg_u8_cols,
            ))

    def _attach_perf(self, state):
        """Ride the recorder's CUMULATIVE report on the returned state
        (every chunk/segment this backend has dispatched so far)."""
        if self.perf is None:
            return state
        from tsspark_tpu.perf import attach_perf

        return attach_perf(state, self.perf.report())

    def _rescue_pass(self, state, ds, y, mask, cap, floor, regressors,
                     conditions, u8):
        """GN-diagonal multi-start refit of the stuck tail (see __init__)."""
        from tsspark_tpu.ops import lbfgs

        if state.status is None:
            return state
        idx = np.flatnonzero(np.isin(
            np.asarray(state.status),
            (lbfgs.STATUS_FLOOR, lbfgs.STATUS_STALLED),
        ))
        if idx.size == 0:
            return state
        bkr = TpuBackend(
            self.config,
            dataclasses.replace(self.solver_config, precond="gn_diag"),
            chunk_size=self.chunk_size, iter_segment=self.iter_segment,
            on_segment=self.on_segment, length_buckets=1, rescue=False,
            mesh=self.mesh, shard_config=self.shard_config,
            compact=self.compact, perf=self.perf,
        )
        y = np.asarray(y)
        r = lambda a: None if a is None else np.asarray(a)[idx]
        ds2 = ds if np.asarray(ds).ndim == 1 else np.asarray(ds)[idx]
        kw = dict(
            mask=r(mask if mask is not None
                   else np.isfinite(y).astype(np.float32)),
            cap=r(cap), floor=r(floor), regressors=r(regressors),
            conditions=None if conditions is None else {
                k: r(v) for k, v in conditions.items()
            },
            reg_u8_cols=u8,
        )
        warm = bkr.fit(ds2, y[idx], init=np.asarray(state.theta)[idx], **kw)
        fresh = bkr.fit(ds2, y[idx], **kw)
        # Keep-best with a margin, incumbents first: a restart that merely
        # ties on loss must not basin-hop the parameters (warm-start
        # continuity; see select_better_state).
        redo = select_better_state(warm, fresh, margin=KEEP_BEST_MARGIN)
        orig = jax.tree.map(lambda a: np.asarray(a)[idx], state)
        best = select_better_state(orig, redo, margin=KEEP_BEST_MARGIN)
        # n_iters reports work actually SPENT on the series (both starts
        # ran regardless of which point won); patch_state accumulates it
        # onto the main solve's count.
        best = best._replace(n_iters=(
            np.asarray(warm.n_iters) + np.asarray(fresh.n_iters)
        ))
        return patch_state(state, idx, best)

    def _fit_main(self, ds, y, mask=None, cap=None, floor=None,
                  regressors=None, init=None, conditions=None,
                  max_iters_dynamic=None, gn_precond_dynamic=None,
                  use_init_dynamic=None, reg_u8_cols=None):
        # Host numpy end-to-end until each chunk's single fit dispatch:
        # a device array here would ship the whole batch over the link only
        # for prepare_fit_data to pull it back for the numpy prep.
        y = np.asarray(y)
        ds = np.asarray(ds)
        b = y.shape[0]
        # Floor the padded chunk at 32 rows: tiny batches are dominated by
        # per-shape compile + dispatch overhead (round-3 verdict, Weak #5),
        # and a streaming driver refits a DIFFERENT touched-series count
        # every micro-batch — without the floor each size compiles its own
        # program.  32 inert rows cost nothing on device; one compiled
        # shape serves every b <= 32 for a given calendar.
        c = min(self.chunk_size, max(32, _next_pow2(b)))
        # Indicator-column split for the packed transfer path, decided ONCE
        # for the whole call: it is a static argument of the jitted fit, so
        # a per-chunk decision could flip and recompile mid-stream.  Skipped
        # when the packed path is unreachable (segmented solves) — the
        # detection is a full O(B*T*R) host scan.
        u8 = reg_u8_cols
        segmented = bool(
            self.iter_segment
            and self.iter_segment < self.solver_config.max_iters
        )
        if (u8 is None and regressors is not None and not segmented
                and ds.ndim == 1):
            u8 = _indicator_reg_cols(np.asarray(regressors))
        dyn = dict(
            max_iters_dynamic=max_iters_dynamic,
            gn_precond_dynamic=gn_precond_dynamic,
            use_init_dynamic=use_init_dynamic,
        )
        # Ragged-length bucketing (shared-grid batches only): fit each
        # length bucket on its own sliced time window so short series stop
        # paying device work for the longest one.  Masked cells are exact
        # zeros in every reduction, so this changes results only at f32
        # reduction-order level (tests/test_bucketing.py asserts parity).
        if ds.ndim == 1:
            plan = self._plan_length_buckets(y, mask)
            if plan is not None:
                sub = TpuBackend(
                    self.config, self.solver_config,
                    chunk_size=self.chunk_size,
                    iter_segment=self.iter_segment,
                    on_segment=self.on_segment,
                    length_buckets=1,
                    rescue=False,  # the top-level fit rescues the whole batch
                    mesh=self.mesh, shard_config=self.shard_config,
                    compact=self.compact, perf=self.perf,
                )
                states = []
                for idx, lo_t, hi_t in plan:
                    r2 = lambda a: None if a is None \
                        else np.asarray(a)[idx][:, lo_t:hi_t]
                    r1 = lambda a: None if a is None else np.asarray(a)[idx]
                    rflex = lambda a: None if a is None else (
                        r2(a) if np.asarray(a).ndim >= 2 else r1(a)
                    )
                    states.append(sub.fit(
                        ds[lo_t:hi_t], r2(y), mask=r2(mask), cap=r2(cap),
                        floor=rflex(floor), regressors=r2(regressors),
                        init=r1(init),
                        conditions=None if conditions is None else {
                            k2: r2(v) for k2, v in conditions.items()
                        },
                        reg_u8_cols=u8, **dyn,
                    ))
                inv = np.argsort(np.concatenate([p[0] for p in plan]))
                return jax.tree.map(
                    lambda a: a[inv], _concat_states(states)
                )
        if b <= c:
            return self._fit_padded(
                ds, y, mask, cap, floor, regressors, init, conditions, c,
                u8, dyn,
            )

        states = []
        for lo in range(0, b, c):
            hi = min(lo + c, b)
            sl = lambda a: None if a is None else np.asarray(a)[lo:hi]
            slc = lambda d: None if d is None else {
                k: np.asarray(v)[lo:hi] for k, v in d.items()
            }
            states.append(
                self._fit_padded(
                    ds if ds.ndim == 1 else ds[lo:hi],
                    y[lo:hi], sl(mask), sl(cap), sl(floor), sl(regressors),
                    sl(init), slc(conditions), c, u8, dyn,
                )
            )
        return _concat_states(states)

    def _fit_padded(self, ds, y, mask, cap, floor, regressors, init,
                    conditions, c, reg_u8_cols=None, dyn=None):
        b = y.shape[0]
        if b < c:
            if ds.ndim == 2:
                # Dummy rows reuse the first series' grid (inert: mask == 0).
                ds = np.concatenate(
                    [ds, np.broadcast_to(ds[:1], (c - b,) + ds.shape[1:])]
                )
            # Dummy series: all-masked, y=0. Their loss is priors-only and
            # converges immediately; results are sliced away below.
            y = _pad_batch(y, c)
            mask = _pad_batch(
                mask if mask is not None else np.isfinite(y), c
            ).astype(y.dtype).copy()
            mask[b:] = 0.0
            cap = _pad_batch(cap, c) if cap is not None else None
            if cap is not None:
                cap = cap.copy()
                cap[b:] = 1.0  # keep logistic cap positive
            floor = _pad_batch(floor, c) if floor is not None else None
            regressors = _pad_batch(regressors, c) if regressors is not None else None
            init = _pad_batch(init, c) if init is not None else None
            if conditions is not None:
                conditions = {
                    k: _pad_batch(v, c) for k, v in conditions.items()
                }
        if self.mesh is not None:
            state = self._fit_sharded_chunk(
                ds, y, mask, cap, floor, regressors, init, conditions,
                dyn, reg_u8_cols,
            )
            return _slice_state(state, 0, b)
        state = self._model.fit(
            ds, y, mask=mask, cap=cap, floor=floor, regressors=regressors,
            init=init, iter_segment=self.iter_segment,
            on_segment=self.on_segment, conditions=conditions,
            reg_u8_cols=reg_u8_cols, recorder=self.perf,
            compact=self.compact,
            compact_multiple=self._compact_multiple(), **(dyn or {}),
        )
        return _slice_state(state, 0, b)

    def _fit_sharded_chunk(self, ds, y, mask, cap, floor, regressors,
                           init, conditions, dyn=None, reg_u8_cols=None):
        """One padded chunk through the multi-chip sharded program.

        The traced phase controls (dyn) are folded into an equivalent
        static solver — same normalization as ProphetModel.fit's
        non-packable fallback; the one-compiled-program-for-both-phases
        trick is a single-device transfer optimization the mesh path does
        not need (its inputs are sharded across devices, not re-shipped
        per phase).

        Transfer: shared-grid batches with an exact 0/1 mask and finite
        observed y ride the packed transit (fit_sharded_packed — each
        device receives only its shard of the packed bytes); everything
        else falls back to the plain sharded feed."""
        from tsspark_tpu.config import ShardingConfig
        from tsspark_tpu.models.prophet.design import pack_fit_data
        from tsspark_tpu.parallel import sharding as sharding_mod

        solver = self.solver_config
        theta0 = init
        d = dyn or {}
        if any(v is not None for v in d.values()):
            # Partial controls get the same normalization ProphetModel.fit
            # applies: missing depth = the solver's own cap, missing metric
            # flag = resolved_precond, missing init flag = honor init.
            mi = d.get("max_iters_dynamic")
            gp = d.get("gn_precond_dynamic")
            ui = d.get("use_init_dynamic")
            solver = dataclasses.replace(
                solver,
                max_iters=solver.max_iters if mi is None else int(mi),
                precond=(
                    solver.resolved_precond(self.config.growth)
                    if gp is None else ("gn_diag" if bool(gp) else "none")
                ),
            )
            if ui is not None and not bool(ui):
                theta0 = None
        data, meta = self._model.prepare(
            ds, y, mask=mask, cap=cap, floor=floor, regressors=regressors,
            conditions=conditions, as_numpy=True,
        )
        # Same packable predicate as ProphetModel.fit (design.
        # packable_batch).  pack_fit_data's own validation (finite
        # observed y, reg_u8_cols columns still 0/1) stays a LOUD failure
        # here too — those are contract violations the single-device path
        # surfaces, not conditions to silently reroute around.
        packable = packable_batch(ds, data.mask)
        if self.shard_config is not None:
            shard_cfg = self.shard_config
        else:
            # Default layout takes the axis NAMES from the mesh itself so
            # custom-named meshes work without a matching ShardingConfig.
            # The conventional names win over position: a mesh declared
            # ("time", "series") must not get its axes swapped just
            # because "series" is not first (ADVICE r4).  The series-axis
            # choice is shared with _compact_multiple via
            # _mesh_series_axis.
            names = self.mesh.axis_names
            series_ax = _mesh_series_axis(self.mesh)
            rest = [n for n in names if n != series_ax]
            time_ax = (
                "time" if "time" in rest else (rest[0] if rest else None)
            )
            shard_cfg = ShardingConfig(
                series_axis=series_ax,
                time_axis=time_ax,
            )
        theta0 = None if theta0 is None else jnp.asarray(theta0)
        dispatch = (
            self.perf.dispatch(int(y.shape[0]), kind="chunk")
            if self.perf is not None else contextlib.nullcontext()
        )
        with dispatch:
            if packable:
                packed, u8 = pack_fit_data(
                    data, meta, ds, reg_u8_cols=reg_u8_cols,
                    collapse_cap=self.config.growth != "logistic",
                )
                res = sharding_mod.fit_sharded_packed(
                    packed, u8, theta0, self.config, solver, self.mesh,
                    shard_cfg,
                )
            else:
                res = sharding_mod.fit_sharded(
                    data, theta0, self.config, solver, self.mesh, shard_cfg,
                )
            if self.perf is not None:
                jax.block_until_ready(res.theta)
        if self.on_segment is not None:
            self.on_segment()
        return FitState(
            theta=res.theta, meta=meta, loss=res.f,
            grad_norm=res.grad_norm, converged=res.converged,
            n_iters=res.n_iters, status=res.status,
        )

    def fit_twophase(self, ds, y, mask=None, cap=None, floor=None,
                     regressors=None, init=None, conditions=None,
                     phase1_iters: int = 12):
        """Straggler-compacted fit: short lockstep phase, then finish only
        the unconverged tail.

        The batched solver advances every series in lockstep, so one slow
        series makes the whole chunk pay full depth — measured on the M5
        workload, mean iterations to converge is ~3 while <2% of series need
        more than ``phase1_iters``.  Phase 1 fits everything with a
        ``phase1_iters`` cap; phase 2 gathers the unconverged series into
        one small compacted batch and continues only those (warm-started
        from their phase-1 parameters, with the GN-diagonal initial metric
        — stragglers are by construction the ill-conditioned tail) at the
        full ``max_iters`` depth.  Device work drops from
        O(B * max_iters) to O(B * phase1_iters + stragglers * max_iters).

        Both phases ride the TRACED phase controls (fit_core's *_dynamic
        args), so on the packed path they share ONE compiled program; the
        straggler batch is additionally padded to phase 1's chunk size so
        no second program shape is compiled either.  Segmented solves fall
        back to per-phase static configs (bounded dispatches win there).
        """
        # Indicator-column pinning: phase 2 refits a SUBSET of rows, where
        # a continuous column could coincidentally look binary and flip the
        # jit-static u8 split — decide once on the full batch and thread
        # the decision through every phase (and the multi-start refits).
        # Segmented solves never reach the packed path, so skip the
        # O(B*T*R) host scan there (ADVICE r3); mesh-sharded solves DO
        # (fit_sharded_packed).
        segmented_2p = bool(
            self.iter_segment
            and self.iter_segment < self.solver_config.max_iters
        )
        u8 = (
            _indicator_reg_cols(np.asarray(regressors))
            if (regressors is not None and not segmented_2p
                and np.asarray(ds).ndim == 1) else None
        )
        if self.iter_segment and self.iter_segment < self.solver_config.max_iters:
            phase1_state = self._phase1(phase1_iters).fit(
                ds, y, mask=mask, cap=cap, floor=floor,
                regressors=regressors, init=init, conditions=conditions,
            )
        else:
            phase1_state = self.fit(
                ds, y, mask=mask, cap=cap, floor=floor,
                regressors=regressors, init=init, conditions=conditions,
                reg_u8_cols=u8,
                **phase1_dynamic_args(phase1_iters, init is not None),
            )
        state = phase1_state
        # Stragglers = unconverged only.  fit_twophase is the SPEED-first
        # entry point: widening the set with stuck exits (FLOOR/STALLED)
        # was measured at ~60% more device work for <= 0.1 nats/series on
        # bench-shaped data, because 60-80% of an M5-like batch exits via
        # the f32 floor legitimately.  Quality-first callers use plain
        # ``fit``, whose rescue pass refits exactly those stuck exits.
        idx = np.flatnonzero(~np.asarray(state.converged))
        if idx.size == 0:
            return state
        idx = idx[difficulty_order(np.asarray(state.grad_norm)[idx])]
        b = np.asarray(y).shape[0]
        c = min(self.chunk_size, _next_pow2(b))
        pad = (-idx.size) % c

        def sub(a, fill=0.0):
            if a is None:
                return None
            a = np.asarray(a)[idx]
            return np.concatenate(
                [a, np.full((pad,) + a.shape[1:], fill, a.dtype)]
            ) if pad else a

        # Warm continuation only: this set is series still PROGRESSING at
        # the phase-1 cap (stuck exits carry status FLOOR/STALLED and are
        # the rescue pass's job) — measured round 4, a fresh-ridge restart
        # won 0/120 of these with zero total gain, so the former
        # multi-start second solve bought nothing for its cost.
        if self.iter_segment and self.iter_segment < self.solver_config.max_iters:
            fit2 = self._straggler_backend().fit
            dyn2 = {}
        else:
            fit2 = self.fit
            dyn2 = phase2_dynamic_args(self.solver_config)
        kwargs = dict(
            mask=sub(mask if mask is not None
                     else np.isfinite(np.asarray(y)).astype(np.float32)),
            cap=sub(cap, fill=1.0), floor=sub(floor),
            regressors=sub(regressors),
            init=sub(np.asarray(state.theta)),
            conditions=None if conditions is None else {
                k: sub(v) for k, v in conditions.items()
            },
            reg_u8_cols=u8,
        )
        ds2 = ds if np.asarray(ds).ndim == 1 else sub(np.asarray(ds))
        # Phase 1's fit already emitted the one full-batch out-of-span
        # changepoint warning; the compacted refit must not add a second
        # copy with subset counts.
        from tsspark_tpu.models.prophet.design import (
            changepoint_span_warning_suppressed as _no_cp_warn,
        )

        with _no_cp_warn():
            state2 = fit2(ds2, sub(y), **kwargs, **dyn2)
        if pad:
            state2 = _slice_state(state2, 0, idx.size)
        return self._attach_perf(patch_state(state, idx, state2))

    def _derived(self, **solver_overrides) -> "TpuBackend":
        """Same backend with SolverConfig fields replaced (keeps chunking
        and liveness wiring in one place).  Derived backends are internal
        phase workers: no auto-bucketing, no rescue pass of their own."""
        return TpuBackend(
            self.config,
            dataclasses.replace(self.solver_config, **solver_overrides),
            chunk_size=self.chunk_size,
            iter_segment=self.iter_segment,
            on_segment=self.on_segment,
            length_buckets=1,
            rescue=False,
            mesh=self.mesh, shard_config=self.shard_config,
            compact=self.compact, perf=self.perf,
        )

    def _phase1(self, phase1_iters: int) -> "TpuBackend":
        # Plain metric pinned: the GN default ("auto") is the FULL-depth
        # choice; at phase-1's short lockstep depth the plain metric
        # converges roughly twice as many series by the cap (config.py),
        # and the packed path pins the same thing via
        # gn_precond_dynamic=False — the two modes must agree.
        return self._derived(max_iters=phase1_iters, precond="none")

    def _straggler_backend(self) -> "TpuBackend":
        """Full-depth backend for the compacted unconverged tail, with the
        GN-diagonal initial metric: stragglers are by construction the
        ill-conditioned series the plain metric stalls on (SolverConfig.
        precond), while the fast majority never pays for it."""
        return self._derived(precond="gn_diag")

    # Memory bound for one predictive-sampling program: the trend
    # simulation materializes an (S, B_chunk, T) float32 tensor, so the
    # series chunk must shrink with samples x grid length (30,490 series x
    # 2,000 grid points x 1,000 samples would be ~244 GB unchunked).
    _PREDICT_ELEMS = 1 << 28  # ~1 GB of f32 per sample tensor

    def predict(self, state, ds, cap=None, regressors=None, seed=0,
                num_samples=None, conditions=None):
        b = np.asarray(state.theta).shape[0]
        ds_np = np.asarray(ds)
        t_len = ds_np.shape[-1]
        n_s = (
            self.config.uncertainty_samples if num_samples is None
            else num_samples
        ) or 1
        # Round DOWN to a power of two: rounding up would let the sample
        # tensor overshoot the element budget by up to 2x.  No floor above
        # one series — a floor of 64 let huge num_samples * grid products
        # overshoot the ~1 GB budget 64-fold (ADVICE r3); at c=1 the chunk
        # tensor is (S, 1, T), within budget for any S * T <= the budget.
        c = max(1, self._PREDICT_ELEMS // max(n_s * t_len, 1))
        c = min(_next_pow2(c + 1) // 2, self.chunk_size, _next_pow2(b))
        if b <= c:
            return self._model.predict(
                state, ds, cap=cap, regressors=regressors, seed=seed,
                num_samples=num_samples, conditions=conditions,
            )
        # One device->host pull up front; per-chunk slicing then stays on
        # host views (the fit path's rule: never re-ship the batch).
        # Scalar / shared-(T,) cap and condition inputs are normalized to
        # per-series (B, T) views first — the unchunked path accepts them
        # via broadcasting, and slicing them along axis 0 would otherwise
        # cut the TIME axis.
        state = jax.tree.map(np.asarray, state)
        bt = lambda a: None if a is None else np.broadcast_to(
            np.asarray(a), (b, t_len)
        )
        cap = bt(cap)
        conditions = None if conditions is None else {
            k: bt(v) for k, v in conditions.items()
        }
        regressors = None if regressors is None else np.asarray(regressors)
        outs = []
        for ci, lo in enumerate(range(0, b, c)):
            hi = min(lo + c, b)
            sl = lambda a: _slice_repeat_pad(a, lo, hi, c)
            outs.append(self._model.predict(
                jax.tree.map(sl, state),
                ds_np if ds_np.ndim == 1 else sl(ds_np),
                cap=sl(cap), regressors=sl(regressors),
                # Independent, well-mixed draws per chunk: integer seed
                # arithmetic (seed + lo) would collide across predict
                # calls whose user seeds differ by less than the batch.
                seed=int(
                    np.random.SeedSequence((seed, ci)).generate_state(1)[0]
                ),
                num_samples=num_samples,
                conditions=None if conditions is None else {
                    k: sl(v) for k, v in conditions.items()
                },
            ))
            if hi - lo < c:
                outs[-1] = {
                    k: np.asarray(v)[: hi - lo] for k, v in outs[-1].items()
                }
        return {
            k: np.concatenate([np.asarray(o[k]) for o in outs], axis=0)
            for k in outs[0]
        }

    def components(self, state, ds, cap=None, regressors=None,
                   conditions=None):
        # Deterministic decomposition, but still a handful of (B, T)
        # arrays per component block: chunk the series axis the same way
        # predict does (without the samples factor in the budget).
        b = np.asarray(state.theta).shape[0]
        ds_np = np.asarray(ds)
        t_len = ds_np.shape[-1]
        c = max(64, self._PREDICT_ELEMS // max(t_len, 1))
        c = min(_next_pow2(c + 1) // 2, self.chunk_size, _next_pow2(b))
        if b <= c:
            return self._model.components(
                state, ds, cap=cap, regressors=regressors,
                conditions=conditions,
            )
        state = jax.tree.map(np.asarray, state)
        bt = lambda a: None if a is None else np.broadcast_to(
            np.asarray(a), (b, t_len)
        )
        cap = bt(cap)
        conditions = None if conditions is None else {
            k: bt(v) for k, v in conditions.items()
        }
        regressors = None if regressors is None else np.asarray(regressors)
        outs = []
        for lo in range(0, b, c):
            hi = min(lo + c, b)
            sl = lambda a: _slice_repeat_pad(a, lo, hi, c)
            out = self._model.components(
                jax.tree.map(sl, state),
                ds_np if ds_np.ndim == 1 else sl(ds_np),
                cap=sl(cap), regressors=sl(regressors),
                conditions=None if conditions is None else {
                    k: sl(v) for k, v in conditions.items()
                },
            )
            outs.append({
                k: np.asarray(v)[: hi - lo] for k, v in out.items()
            })
        return {
            k: np.concatenate([o[k] for o in outs], axis=0)
            for k in outs[0]
        }


def phase1_dynamic_args(phase1_iters: int, use_init: bool,
                        packed: bool = False) -> dict:
    """THE shallow-phase dispatch policy, shared by ``fit_twophase`` and
    the orchestrator's chunk workers (tsspark_tpu.orchestrate): lockstep
    depth capped at ``phase1_iters``, plain metric (the GN default is the
    FULL-depth choice — at short depth the plain metric converges roughly
    twice as many series by the cap), ridge init unless a warm start is
    supplied.  ``packed=True`` renames the init flag to fit_core_packed's
    spelling.  Keeping both phases' traced-arg triples in one place is
    what guarantees the in-memory API and the process-isolated bench
    path stay numerically aligned (round-4 verdict, Weak #2)."""
    d = dict(
        max_iters_dynamic=np.int32(phase1_iters),
        gn_precond_dynamic=np.bool_(False),
        use_init_dynamic=np.bool_(use_init),
    )
    if packed:
        d["use_theta0_dynamic"] = d.pop("use_init_dynamic")
    return d


def phase2_dynamic_args(solver_config, packed: bool = False) -> dict:
    """THE deep-phase dispatch policy (see phase1_dynamic_args): full
    solver depth, GN-diagonal initial metric (stragglers are by
    construction the ill-conditioned tail), warm-started from phase-1
    parameters."""
    d = dict(
        max_iters_dynamic=np.int32(solver_config.max_iters),
        gn_precond_dynamic=np.bool_(True),
        use_init_dynamic=np.bool_(True),
    )
    if packed:
        d["use_theta0_dynamic"] = d.pop("use_init_dynamic")
    return d


def tune_phase1_depth(depth: int, frac_unconv: float,
                      max_iters: int) -> int:
    """THE adaptive phase-1 depth policy, applied once after chunk 0:
    deepen only on a PATHOLOGICAL first chunk (a quarter still
    progressing — measured on the M5 shape the unconverged set is
    depth-flat, it is the ill-conditioned tail that needs phase 2's GN
    metric, not more plain lockstep), shallow out when virtually
    everything converges early.  One definition shared by the chunk-file
    fit worker (``orchestrate``) and the mesh-resident path
    (``tsspark_tpu.resident``) so the two paths' depth decisions — and
    therefore their per-series results — cannot drift."""
    if frac_unconv > 0.25:
        return min(int(depth) * 2, int(max_iters))
    if frac_unconv < 0.005 and int(depth) > 8:
        return max(8, int(depth) * 2 // 3)
    return int(depth)


def difficulty_order(grad_norm: np.ndarray) -> np.ndarray:
    """Argsort for compacting stragglers, hardest first.

    Each padded sub-chunk's lockstep solve runs until ITS slowest member
    converges, so grouping similar-difficulty series lets easy sub-chunks
    exit early instead of every sub-chunk paying for one deep series.
    Phase-1 exit grad-norm is the difficulty proxy; NaN grad norms
    (diverged series) count as hardest, not easiest — argsort would
    otherwise sort NaN last and seat the most broken series in the
    "easy" sub-chunk, inverting the grouping's intent.  Callers patch
    results back by index, so the reorder never changes results."""
    g = np.asarray(grad_norm, np.float64)
    g = np.where(np.isnan(g), np.inf, g)
    return np.argsort(-g, kind="stable")


def patch_state(state: FitState, idx: np.ndarray, sub: FitState) -> FitState:
    """Scatter a compacted follow-up fit back into the full-batch FitState.

    ``sub`` holds results for ``state``'s rows ``idx`` (same data, deeper
    solve).  Iteration counts accumulate across phases; scaling meta is
    recomputed deterministically from the same rows, so either copy works.
    """

    def scatter(full, part, accumulate=False):
        if full is None or part is None:
            return full
        out = np.asarray(full).copy()
        out[idx] = (out[idx] + np.asarray(part)) if accumulate \
            else np.asarray(part)
        return jnp.asarray(out) if isinstance(full, jax.Array) else out

    return FitState(
        theta=scatter(state.theta, sub.theta),
        meta=state.meta,
        loss=scatter(state.loss, sub.loss),
        grad_norm=scatter(state.grad_norm, sub.grad_norm),
        converged=scatter(state.converged, sub.converged),
        n_iters=scatter(state.n_iters, sub.n_iters, accumulate=True),
        status=scatter(state.status, sub.status),
    )


# One ladder for chunk padding and compaction widths (see
# sharding.next_pow2); the alias keeps this module's many call sites
# unchanged.
from tsspark_tpu.parallel.sharding import next_pow2 as _next_pow2  # noqa: E402,E501
