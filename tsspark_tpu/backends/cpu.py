"""CPU reference backend: per-series scipy L-BFGS-B.

This is the analog of the reference's CPU executor path (per-series scipy
L-BFGS MAP fits inside Spark ``mapPartitions`` workers, BASELINE.json:5) and
serves as the parity oracle for the batched TPU solver: same loss, same
design tensors, independent battle-tested optimizer.  It is intentionally a
straight per-series Python loop — its job is correctness, not speed.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import scipy.optimize

from tsspark_tpu.backends.registry import ForecastBackend, register_backend
from tsspark_tpu.models.prophet import predict as predict_mod
from tsspark_tpu.models.prophet.design import FitData, prepare_fit_data
from tsspark_tpu.models.prophet.loss import neg_log_posterior
from tsspark_tpu.models.prophet.init import initial_theta
from tsspark_tpu.models.prophet.model import FitState


@register_backend
class CpuBackend(ForecastBackend):
    name = "cpu"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._cpu = jax.devices("cpu")[0]
        # Single-series objective jitted once on CPU; scipy drives it.
        cfg = self.config

        @jax.jit
        def vag(theta, data):
            f = lambda th: neg_log_posterior(th[None, :], data, cfg)[0]
            return jax.value_and_grad(f)(theta)

        self._vag = vag

    def fit(self, ds, y, mask=None, cap=None, floor=None, regressors=None,
            init=None, conditions=None):
        with jax.default_device(self._cpu):
            data, meta = prepare_fit_data(
                ds, y, self.config, mask=mask, cap=cap, floor=floor,
                regressors=regressors, conditions=conditions,
            )
            # Same warm-start policy as the TPU path (SolverConfig.init),
            # so parity runs compare solver behavior, not starting points.
            theta0 = init if init is not None else initial_theta(
                data, self.config, self.solver_config
            )
            theta0 = np.asarray(theta0, np.float64)
            b = theta0.shape[0]
            out = np.empty_like(theta0)
            losses = np.empty(b)
            grad_norms = np.empty(b)
            conv = np.empty(b, bool)
            n_iters = np.empty(b, np.int32)
            shared_x = data.X_season.ndim == 2

            for i in range(b):
                data_i = FitData(
                    t=data.t[i : i + 1],
                    y=data.y[i : i + 1],
                    mask=data.mask[i : i + 1],
                    s=data.s[i : i + 1],
                    cap=data.cap[i : i + 1],
                    X_season=data.X_season if shared_x else data.X_season[i : i + 1],
                    X_reg=data.X_reg[i : i + 1],
                    prior_scales=data.prior_scales,
                    mult_mask=data.mult_mask,
                )

                def f_and_g(th):
                    f, g = self._vag(jnp.asarray(th, jnp.float32), data_i)
                    return float(f), np.asarray(g, np.float64)

                res = scipy.optimize.minimize(
                    f_and_g,
                    theta0[i],
                    jac=True,
                    method="L-BFGS-B",
                    options={
                        "maxiter": self.solver_config.max_iters,
                        "ftol": 1e-9,
                        "gtol": 1e-7,
                    },
                )
                out[i] = res.x
                losses[i] = res.fun
                grad_norms[i] = np.abs(np.asarray(res.jac)).max()
                conv[i] = res.success
                n_iters[i] = res.nit

            return FitState(
                theta=jnp.asarray(out, jnp.float32),
                meta=meta,
                loss=jnp.asarray(losses, jnp.float32),
                grad_norm=jnp.asarray(grad_norms, jnp.float32),
                converged=jnp.asarray(conv),
                n_iters=jnp.asarray(n_iters),
            )

    def predict(self, state, ds, cap=None, regressors=None, seed=0,
                num_samples=None, conditions=None):
        with jax.default_device(self._cpu):
            data = predict_mod.prepare_predict_data(
                ds, state.meta, self.config, cap=cap, regressors=regressors,
                conditions=conditions,
            )
            return predict_mod.forecast_jit(
                state.theta, data, state.meta, self.config,
                key=jax.random.PRNGKey(seed), num_samples=num_samples,
            )
