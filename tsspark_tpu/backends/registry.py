"""ForecastBackend plugin registry.

Mirrors the reference's ``ForecastBackend`` registry (BASELINE.json:5 — the
TPU path there is exposed as ``backend="tpu"`` behind an existing plugin
registry).  Backends are classes implementing fit/predict over padded array
batches; selection is by name with optional keyword overrides.

Built-ins:
  * "cpu" — per-series scipy L-BFGS-B reference path (parity oracle).
  * "tpu" — the batched JAX path (runs on TPU when present, else any JAX
    backend; the name states intent, matching the reference's API).
"""

from __future__ import annotations

import abc
from typing import Dict, Optional, Type

from tsspark_tpu.config import ProphetConfig, SolverConfig


class ForecastBackend(abc.ABC):
    """A strategy for executing batched Prophet fits."""

    name: str = "abstract"

    def __init__(
        self,
        config: ProphetConfig = ProphetConfig(),
        solver_config: SolverConfig = SolverConfig(),
        **kwargs,
    ):
        from tsspark_tpu.utils.platform import (
            enable_persistent_compile_cache,
        )

        # One chokepoint for every backend: amortize the multi-second XLA
        # compile across processes (round-3 verdict, Weak #5).
        enable_persistent_compile_cache()
        self.config = config
        self.solver_config = solver_config

    @abc.abstractmethod
    def fit(self, ds, y, mask=None, cap=None, floor=None, regressors=None,
            init=None, conditions=None):
        """Fit a (B, T) batch; returns a FitState."""

    @abc.abstractmethod
    def predict(self, state, ds, cap=None, regressors=None, seed=0,
                num_samples=None, conditions=None):
        """Forecast a fitted state on a time grid; returns dict of arrays."""

    def components(self, state, ds, cap=None, regressors=None,
                   conditions=None):
        """Per-block component arrays for a fitted state.

        Decomposition is pure model math on the fitted parameters — identical
        for every backend — so the base class provides it; backends override
        only if they carry a differently-shaped state.
        """
        from tsspark_tpu.models.prophet.model import ProphetModel

        return ProphetModel(self.config, self.solver_config).components(
            state, ds, cap=cap, regressors=regressors, conditions=conditions
        )


_REGISTRY: Dict[str, Type[ForecastBackend]] = {}


def register_backend(cls: Type[ForecastBackend]) -> Type[ForecastBackend]:
    """Class decorator: register a backend under its ``name`` attribute."""
    if not getattr(cls, "name", None) or cls.name == "abstract":
        raise ValueError(f"backend class {cls.__name__} needs a name attribute")
    if _REGISTRY.get(cls.name) not in (None, cls):
        raise ValueError(f"backend {cls.name!r} already registered")
    _REGISTRY[cls.name] = cls
    return cls


def get_backend(
    name: str,
    config: Optional[ProphetConfig] = None,
    solver_config: Optional[SolverConfig] = None,
    **kwargs,
) -> ForecastBackend:
    """Instantiate a registered backend by name."""
    _ensure_builtins()
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown backend {name!r}; available: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name](
        config=config or ProphetConfig(),
        solver_config=solver_config or SolverConfig(),
        **kwargs,
    )


#: Where resilient fits degrade to when the accelerator path's retry
#: budget is exhausted: the per-series scipy reference path — slow, but
#: it has no accelerator runtime, no XLA program size limits, and no
#: lockstep batch to poison, so it finishes runs the batched path cannot.
DEGRADED_BACKEND = "cpu"


def degraded_backend(
    config: Optional[ProphetConfig] = None,
    solver_config: Optional[SolverConfig] = None,
    **kwargs,
) -> ForecastBackend:
    """The graceful-degradation backend (see ``DEGRADED_BACKEND``).

    Lives here rather than in the orchestrator so the which-backend-
    degrades-to-what decision sits with the registry, next to the
    backends themselves; ``orchestrate.fit_resilient`` calls this after
    exhausting the TPU path (docs/RESILIENCE.md)."""
    return get_backend(DEGRADED_BACKEND, config, solver_config, **kwargs)


def list_backends():
    _ensure_builtins()
    return sorted(_REGISTRY)


def _ensure_builtins():
    # Imported lazily to avoid a circular import at package-import time.
    from tsspark_tpu.backends import cpu, tpu  # noqa: F401
