"""ctypes bindings for the native ingest/pivot engine (src/ingest.cpp).

The shared library is compiled on first use with the image's g++ (no
pybind11 here; the C ABI + ctypes keeps the binding dependency-free) and
cached next to the source keyed by a source hash.  Everything degrades to
numpy fallbacks when no compiler is available, so the framework never hard-
requires the native path — it's a speedup, not a dependency.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "ingest.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:12]
    so_path = os.path.join(_DIR, f"_ingest_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    # Compile to a private temp path, then atomically rename: a concurrent
    # process must never dlopen a partially written .so.
    tmp_path = f"{so_path}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", tmp_path,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp_path, so_path)
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        return None
    return so_path


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        lib.bulk_pivot.argtypes = [
            ctypes.c_int64, _i64p, _i64p, _f64p, _f64p,
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.bulk_pivot.restype = None
        lib.store_new.argtypes = [ctypes.c_int64]
        lib.store_new.restype = ctypes.c_void_p
        lib.store_free.argtypes = [ctypes.c_void_p]
        lib.store_series_count.argtypes = [ctypes.c_void_p]
        lib.store_series_count.restype = ctypes.c_int64
        lib.store_series_length.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.store_series_length.restype = ctypes.c_int64
        lib.store_append.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, _i64p, _f64p, _f64p,
        ]
        lib.store_union_grid.argtypes = [
            ctypes.c_void_p, _i64p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.store_union_grid.restype = ctypes.c_int64
        lib.store_materialize.argtypes = [
            ctypes.c_void_p, _i64p, ctypes.c_int64, _f64p,
            ctypes.c_int64, _f64p,
        ]
        lib.pstore_new.argtypes = [ctypes.c_int64]
        lib.pstore_new.restype = ctypes.c_void_p
        lib.pstore_free.argtypes = [ctypes.c_void_p]
        lib.pstore_size.argtypes = [ctypes.c_void_p]
        lib.pstore_size.restype = ctypes.c_int64
        lib.pstore_row_dim.argtypes = [ctypes.c_void_p]
        lib.pstore_row_dim.restype = ctypes.c_int64
        lib.pstore_update.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, _i64p, _f64p,
        ]
        lib.pstore_lookup.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, _i64p, _f64p, _u8p,
        ]
        lib.pstore_lookup.restype = ctypes.c_int64
        lib.pstore_export.argtypes = [ctypes.c_void_p, _i64p, _f64p]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


def bulk_pivot(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
               b: int, t: int) -> np.ndarray:
    """Scatter long-format rows into a NaN-padded (b, t) float64 matrix.

    Last write wins on duplicate (row, col) — matching pandas
    drop_duplicates(keep="last") semantics in the frame layer.
    """
    rows = np.ascontiguousarray(rows, np.int64)
    cols = np.ascontiguousarray(cols, np.int64)
    vals = np.ascontiguousarray(vals, np.float64)
    lib = _load()
    out = np.empty((b, t), np.float64)
    if lib is None:
        out.fill(np.nan)
        out[rows, cols] = vals
        return out
    lib.bulk_pivot(len(vals), rows, cols, vals, out.reshape(-1), b, t)
    return out


class HistoryStore:
    """Bounded per-series observation history (streaming 'absorb' path)."""

    def __init__(self, max_history: int = 4096):
        self.max_history = max_history
        self._lib = _load()
        if self._lib is not None:
            self._handle = ctypes.c_void_p(self._lib.store_new(max_history))
        else:  # numpy fallback: dict of (days, values) arrays
            self._py: dict = {}

    def __del__(self):
        if getattr(self, "_lib", None) is not None and self._handle:
            self._lib.store_free(self._handle)
            self._handle = None

    def __len__(self) -> int:
        if self._lib is not None:
            return int(self._lib.store_series_count(self._handle))
        return len(self._py)

    def series_length(self, sid: int) -> int:
        if self._lib is not None:
            return int(self._lib.store_series_length(self._handle, int(sid)))
        d = self._py.get(int(sid))
        return 0 if d is None else len(d[0])

    def append(self, sids: np.ndarray, days: np.ndarray, vals: np.ndarray
               ) -> None:
        sids = np.ascontiguousarray(sids, np.int64)
        days = np.ascontiguousarray(days, np.float64)
        vals = np.ascontiguousarray(vals, np.float64)
        if self._lib is not None:
            self._lib.store_append(self._handle, len(sids), sids, days, vals)
            return
        for sid in np.unique(sids):
            m = sids == sid
            d_new, v_new = days[m], vals[m]
            old = self._py.get(int(sid))
            if old is not None:
                d_new = np.concatenate([old[0], d_new])
                v_new = np.concatenate([old[1], v_new])
            # stable sort + keep last duplicate
            order = np.argsort(d_new, kind="stable")
            d_s, v_s = d_new[order], v_new[order]
            keep = np.ones(len(d_s), bool)
            keep[:-1] = d_s[1:] != d_s[:-1]
            d_s, v_s = d_s[keep], v_s[keep]
            self._py[int(sid)] = (d_s[-self.max_history:],
                                  v_s[-self.max_history:])

    def union_grid(self, sids: np.ndarray) -> np.ndarray:
        sids = np.ascontiguousarray(sids, np.int64)
        if self._lib is not None:
            n = self._lib.store_union_grid(self._handle, sids, len(sids), None)
            grid = np.empty(n, np.float64)
            if n:
                self._lib.store_union_grid(
                    self._handle, sids, len(sids),
                    grid.ctypes.data_as(ctypes.c_void_p),
                )
            return grid
        parts = [self._py[int(s)][0] for s in sids if int(s) in self._py]
        if not parts:
            return np.empty(0, np.float64)
        return np.unique(np.concatenate(parts))

    def materialize(self, sids: np.ndarray, grid: np.ndarray) -> np.ndarray:
        """(B, T) float64 with NaN where a series has no observation."""
        sids = np.ascontiguousarray(sids, np.int64)
        grid = np.ascontiguousarray(grid, np.float64)
        b, t = len(sids), len(grid)
        if self._lib is not None:
            out = np.empty((b, t), np.float64)
            self._lib.store_materialize(
                self._handle, sids, b, grid, t, out.reshape(-1)
            )
            return out
        out = np.full((b, t), np.nan)
        for i, sid in enumerate(sids):
            rec = self._py.get(int(sid))
            if rec is None:
                continue
            idx = np.searchsorted(grid, rec[0])
            ok = (idx < t) & (grid[np.minimum(idx, t - 1)] == rec[0])
            out[i, idx[ok]] = rec[1][ok]
        return out


class ParamTable:
    """Fixed-width float64 rows keyed by int64 id (bulk upsert/gather).

    Double precision because rows carry absolute-time scaling meta
    (``ds_start`` in epoch days ~2e4): float32 quantizes hourly/minute
    warm-start alignment to ~5-minute granularity.

    The native backing store for the streaming warm-start ParamStore: one
    micro-batch update/lookup is two memcpy-bound C calls instead of a
    Python loop over series.  Falls back to a vectorized numpy/dict
    implementation when no compiler is available.
    """

    def __init__(self, row_dim: int):
        self.row_dim = int(row_dim)
        self._lib = _load()
        if self._lib is not None:
            self._handle = ctypes.c_void_p(self._lib.pstore_new(self.row_dim))
        else:
            self._idx: dict = {}          # id -> row number
            self._rows: list = []         # list of np.float64 rows

    def __del__(self):
        if getattr(self, "_lib", None) is not None and self._handle:
            self._lib.pstore_free(self._handle)
            self._handle = None

    def __len__(self) -> int:
        if self._lib is not None:
            return int(self._lib.pstore_size(self._handle))
        return len(self._idx)

    def update(self, ids: np.ndarray, rows: np.ndarray) -> None:
        ids = np.ascontiguousarray(ids, np.int64)
        rows = np.ascontiguousarray(rows, np.float64)
        if rows.shape != (len(ids), self.row_dim):
            raise ValueError(
                f"rows shape {rows.shape} != ({len(ids)}, {self.row_dim})"
            )
        if self._lib is not None:
            self._lib.pstore_update(self._handle, len(ids), ids,
                                    rows.reshape(-1))
            return
        for i, sid in enumerate(ids):
            k = int(sid)
            if k in self._idx:
                self._rows[self._idx[k]] = rows[i].copy()
            else:
                self._idx[k] = len(self._rows)
                self._rows.append(rows[i].copy())

    def lookup(self, ids: np.ndarray):
        """Returns (rows (n, row_dim) float64 zero-filled on miss, found (n,) bool)."""
        ids = np.ascontiguousarray(ids, np.int64)
        n = len(ids)
        out = np.empty((n, self.row_dim), np.float64)
        found = np.empty(n, np.uint8)
        if self._lib is not None:
            self._lib.pstore_lookup(self._handle, n, ids, out.reshape(-1),
                                    found)
            return out, found.astype(bool)
        for i, sid in enumerate(ids):
            row = self._idx.get(int(sid))
            found[i] = row is not None
            out[i] = self._rows[row] if row is not None else 0.0
        return out, found.astype(bool)

    def export(self):
        """All (ids (N,), rows (N, row_dim)) pairs, insertion-ordered."""
        n = len(self)
        ids = np.empty(n, np.int64)
        rows = np.empty((n, self.row_dim), np.float64)
        if self._lib is not None:
            if n:
                self._lib.pstore_export(self._handle, ids, rows.reshape(-1))
            return ids, rows
        for sid, row in self._idx.items():
            ids[row] = sid
            rows[row] = self._rows[row]
        return ids, rows
