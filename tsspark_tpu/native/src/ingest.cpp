// Native ingest/pivot engine for tsspark_tpu.
//
// The reference offloads its runtime hot paths (data movement around the
// fit) to the JVM/native layer; here the two host-side hot paths are:
//
//   1. bulk pivot ("collect"): scatter tens of millions of long-format rows
//      into a padded (B, T) batch before device transfer — threaded scatter
//      with last-write-wins per (row, col).
//   2. streaming history store: per-series bounded ring of (day, value)
//      observations with sorted dedup-append ("absorb") and padded
//      materialization, replacing the pandas concat/dedup/sort per
//      micro-batch in the streaming driver.
//
// Exposed as a C ABI for ctypes (no pybind11 on this image).  All ids are
// pre-factorized int64 codes (string interning stays in Python/pandas,
// which already does it in C).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr double kNaN = std::numeric_limits<double>::quiet_NaN();

struct Series {
  // Kept sorted by day; bounded to max_history newest observations.
  std::vector<double> days;
  std::vector<double> values;
};

struct Store {
  int64_t max_history;
  std::unordered_map<int64_t, Series> series;
};

struct ParamTable {
  // Fixed-width float64 rows in contiguous storage; id -> row index.
  // Double because the rows carry absolute-time scaling meta (ds_start in
  // epoch days ~2e4): float32 would quantize warm-start time alignment to
  // ~5-minute granularity, a real bias at hourly/minute cadence.
  int64_t row_dim;
  std::unordered_map<int64_t, int64_t> index;
  std::vector<double> rows;
  std::vector<int64_t> ids;  // row index -> id (for export)
};

int hardware_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 4 : static_cast<int>(n);
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------- bulk pivot

// Scatter n long-format rows into out[b, t] (row-major), NaN-prefilled.
// Rows arrive in order; for duplicate (row, col) pairs the LAST wins, so the
// parallelization partitions by destination row (each row's writes stay on
// one thread, in input order).  Out-of-range indices are skipped (the Python
// layer rejects them; this is defense in depth, not an API).
void bulk_pivot(int64_t n, const int64_t* rows, const int64_t* cols,
                const double* vals, double* out, int64_t b, int64_t t) {
  std::fill(out, out + b * t, kNaN);
  auto in_range = [=](int64_t i) {
    return rows[i] >= 0 && rows[i] < b && cols[i] >= 0 && cols[i] < t;
  };
  int n_threads = std::min<int64_t>(hardware_threads(), std::max<int64_t>(b, 1));
  if (n < (1 << 16) || n_threads <= 1) {
    for (int64_t i = 0; i < n; ++i) {
      if (in_range(i)) out[rows[i] * t + cols[i]] = vals[i];
    }
    return;
  }
  // Bucket row indices per thread in one pass (O(n) total work instead of
  // every thread scanning all n rows); order within a bucket preserves the
  // input order, keeping last-wins semantics per destination row.
  std::vector<int64_t> counts(n_threads, 0);
  for (int64_t i = 0; i < n; ++i) {
    if (in_range(i)) ++counts[rows[i] % n_threads];
  }
  std::vector<int64_t> offsets(n_threads + 1, 0);
  for (int tid = 0; tid < n_threads; ++tid) {
    offsets[tid + 1] = offsets[tid] + counts[tid];
  }
  std::vector<int64_t> bucketed(offsets[n_threads]);
  std::vector<int64_t> cursor(offsets.begin(), offsets.end() - 1);
  for (int64_t i = 0; i < n; ++i) {
    if (in_range(i)) bucketed[cursor[rows[i] % n_threads]++] = i;
  }
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (int tid = 0; tid < n_threads; ++tid) {
    workers.emplace_back([&, tid] {
      for (int64_t k = offsets[tid]; k < offsets[tid + 1]; ++k) {
        int64_t i = bucketed[k];
        out[rows[i] * t + cols[i]] = vals[i];
      }
    });
  }
  for (auto& w : workers) w.join();
}

// ------------------------------------------------------------ history store

void* store_new(int64_t max_history) {
  auto* s = new Store();
  s->max_history = max_history;
  return s;
}

void store_free(void* handle) { delete static_cast<Store*>(handle); }

int64_t store_series_count(void* handle) {
  return static_cast<int64_t>(static_cast<Store*>(handle)->series.size());
}

int64_t store_series_length(void* handle, int64_t sid) {
  auto& m = static_cast<Store*>(handle)->series;
  auto it = m.find(sid);
  return it == m.end() ? 0 : static_cast<int64_t>(it->second.days.size());
}

// Append n observations (sid code, day, value); per series the result stays
// sorted by day with duplicate days resolved last-write-wins, trimmed to the
// newest max_history points.
void store_append(void* handle, int64_t n, const int64_t* sids,
                  const double* days, const double* vals) {
  auto* store = static_cast<Store*>(handle);
  for (int64_t i = 0; i < n; ++i) {
    Series& s = store->series[sids[i]];
    double d = days[i];
    if (!s.days.empty() && d > s.days.back()) {
      s.days.push_back(d);
      s.values.push_back(vals[i]);
    } else {
      auto it = std::lower_bound(s.days.begin(), s.days.end(), d);
      size_t pos = static_cast<size_t>(it - s.days.begin());
      if (it != s.days.end() && *it == d) {
        s.values[pos] = vals[i];  // duplicate day: last wins
      } else {
        s.days.insert(it, d);
        s.values.insert(s.values.begin() + pos, vals[i]);
      }
    }
    if (static_cast<int64_t>(s.days.size()) > store->max_history) {
      size_t drop = s.days.size() - static_cast<size_t>(store->max_history);
      s.days.erase(s.days.begin(), s.days.begin() + drop);
      s.values.erase(s.values.begin(), s.values.begin() + drop);
    }
  }
}

// Union time grid across the requested series, sorted ascending.  Returns
// the grid length; call with grid == nullptr to size the buffer first.
int64_t store_union_grid(void* handle, const int64_t* sids, int64_t b,
                         double* grid) {
  auto* store = static_cast<Store*>(handle);
  std::vector<double> all;
  for (int64_t i = 0; i < b; ++i) {
    auto it = store->series.find(sids[i]);
    if (it == store->series.end()) continue;
    all.insert(all.end(), it->second.days.begin(), it->second.days.end());
  }
  std::sort(all.begin(), all.end());
  all.erase(std::unique(all.begin(), all.end()), all.end());
  if (grid != nullptr) {
    std::memcpy(grid, all.data(), all.size() * sizeof(double));
  }
  return static_cast<int64_t>(all.size());
}

// Materialize the requested series onto a (sorted) grid: out[b, t] gets the
// value at the matching day or NaN.  Threaded over series.
void store_materialize(void* handle, const int64_t* sids, int64_t b,
                       const double* grid, int64_t t, double* out) {
  auto* store = static_cast<Store*>(handle);
  int n_threads = std::min<int64_t>(hardware_threads(), std::max<int64_t>(b, 1));
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      double* row = out + i * t;
      std::fill(row, row + t, kNaN);
      auto it = store->series.find(sids[i]);
      if (it == store->series.end()) continue;
      const Series& s = it->second;
      size_t gi = 0;
      for (size_t k = 0; k < s.days.size(); ++k) {
        const double* pos =
            std::lower_bound(grid + gi, grid + t, s.days[k]);
        if (pos == grid + t) break;
        gi = static_cast<size_t>(pos - grid);
        if (*pos == s.days[k]) row[gi] = s.values[k];
      }
    }
  };
  if (b < 64 || n_threads <= 1) {
    work(0, b);
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (b + n_threads - 1) / n_threads;
  for (int tid = 0; tid < n_threads; ++tid) {
    int64_t lo = tid * chunk, hi = std::min<int64_t>(lo + chunk, b);
    if (lo >= hi) break;
    workers.emplace_back(work, lo, hi);
  }
  for (auto& w : workers) w.join();
}

// ------------------------------------------------------------- param table
//
// The streaming warm-start state (fitted theta + scaling rows keyed by
// series) lives here so a 30k-series micro-batch update/lookup is two
// memcpy-bound bulk calls instead of a Python loop over series.

void* pstore_new(int64_t row_dim) {
  auto* t = new ParamTable();
  t->row_dim = row_dim;
  return t;
}

void pstore_free(void* handle) { delete static_cast<ParamTable*>(handle); }

int64_t pstore_size(void* handle) {
  return static_cast<int64_t>(static_cast<ParamTable*>(handle)->index.size());
}

int64_t pstore_row_dim(void* handle) {
  return static_cast<ParamTable*>(handle)->row_dim;
}

// Upsert n rows (each row_dim doubles).  Last write wins on duplicate ids
// within one call (matching the Python dict semantics it replaces).
void pstore_update(void* handle, int64_t n, const int64_t* ids,
                   const double* data) {
  auto* t = static_cast<ParamTable*>(handle);
  const int64_t d = t->row_dim;
  for (int64_t i = 0; i < n; ++i) {
    auto [it, inserted] =
        t->index.try_emplace(ids[i], static_cast<int64_t>(t->ids.size()));
    if (inserted) {
      t->ids.push_back(ids[i]);
      t->rows.resize(t->rows.size() + d);
    }
    std::memcpy(t->rows.data() + it->second * d, data + i * d,
                d * sizeof(double));
  }
}

// Gather n rows into out (n x row_dim, zero-filled on miss); found[i] gets
// 1/0.  Returns the number found.  Threaded gather for large batches.
int64_t pstore_lookup(void* handle, int64_t n, const int64_t* ids,
                      double* out, uint8_t* found) {
  auto* t = static_cast<ParamTable*>(handle);
  const int64_t d = t->row_dim;
  std::vector<int64_t> row_of(n);
  int64_t n_found = 0;
  for (int64_t i = 0; i < n; ++i) {  // map probes stay single-threaded
    auto it = t->index.find(ids[i]);
    row_of[i] = it == t->index.end() ? -1 : it->second;
    found[i] = row_of[i] >= 0;
    n_found += found[i];
  }
  auto gather = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      double* dst = out + i * d;
      if (row_of[i] < 0) {
        std::fill(dst, dst + d, 0.0);
      } else {
        std::memcpy(dst, t->rows.data() + row_of[i] * d, d * sizeof(double));
      }
    }
  };
  int n_threads = hardware_threads();
  if (n < 4096 || n_threads <= 1) {
    gather(0, n);
  } else {
    std::vector<std::thread> workers;
    int64_t chunk = (n + n_threads - 1) / n_threads;
    for (int tid = 0; tid < n_threads; ++tid) {
      int64_t lo = tid * chunk, hi = std::min<int64_t>(lo + chunk, n);
      if (lo >= hi) break;
      workers.emplace_back(gather, lo, hi);
    }
    for (auto& w : workers) w.join();
  }
  return n_found;
}

// Dump every (id, row) pair; buffers must hold pstore_size rows.
void pstore_export(void* handle, int64_t* ids_out, double* rows_out) {
  auto* t = static_cast<ParamTable*>(handle);
  std::memcpy(ids_out, t->ids.data(), t->ids.size() * sizeof(int64_t));
  std::memcpy(rows_out, t->rows.data(), t->rows.size() * sizeof(double));
}

}  // extern "C"
