"""Platform selection helper.

This image's sitecustomize force-registers the axon TPU plugin and overrides
the JAX_PLATFORMS environment variable at interpreter start; any CLI that
should honor an explicit ``JAX_PLATFORMS=...`` (e.g. CPU smoke runs while the
TPU is held by another process) must re-assert it at the config level before
the first backend lookup.
"""

from __future__ import annotations

import os

import jax


def honor_env_platforms() -> None:
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


_CACHE_ENABLED = False


def enable_persistent_compile_cache() -> None:
    """Point JAX's persistent compilation cache at a host-keyed user dir.

    The fit program costs seconds to compile (tens of seconds through the
    TPU tunnel) and small-batch users pay it on every fresh process — the
    dominant cost of a one-series fit (round-3 verdict, Weak #5).  The
    persistent cache amortizes it across processes.  Called lazily from
    the backends on first fit; opt out with TSSPARK_NO_COMPILE_CACHE=1 or
    by pointing JAX_COMPILATION_CACHE_DIR somewhere explicit (an explicit
    user setting always wins — we never override it).

    The dir is keyed on host_cpu_tag(): XLA:CPU AOT entries bake in the
    compile machine's feature set and SIGILL on a different VM generation.
    """
    global _CACHE_ENABLED
    if _CACHE_ENABLED:
        return
    _CACHE_ENABLED = True
    if os.environ.get("TSSPARK_NO_COMPILE_CACHE"):
        return
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return  # user already chose a cache location
    if jax.config.jax_compilation_cache_dir:
        return  # caller configured one programmatically
    path = os.path.join(
        os.path.expanduser("~"), ".cache", "tsspark_tpu",
        f"jax_cache_{host_cpu_tag()}",
    )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5
        )
    except Exception:  # cache is an optimization; never fail a fit over it
        pass


def host_cpu_tag() -> str:
    """Host-CPU fingerprint for persistent compile-cache dirs.

    XLA:CPU AOT cache entries bake in the compile machine's feature set;
    loading one on a different VM generation is a documented SIGILL/SIGSEGV
    path.  Keying cache dirs on a hash of the cpuinfo flags line makes
    cross-host reuse impossible (bench.py and tests/conftest.py share this
    single definition so their cache keys can never drift apart).
    """
    import hashlib

    try:
        with open("/proc/cpuinfo") as fh:
            # x86 calls the line "flags"; ARM64 calls it "Features" — the
            # guard must key on actual CPU capabilities on both, not fall
            # through to a kernel string that two different-feature VMs
            # can share.
            line = next(
                l for l in fh
                if l.startswith("flags") or l.startswith("Features")
            )
    except (OSError, StopIteration):
        import platform as _platform

        line = _platform.platform()
    return hashlib.md5(line.encode()).hexdigest()[:8]
