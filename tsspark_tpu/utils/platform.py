"""Platform selection helper.

This image's sitecustomize force-registers the axon TPU plugin and overrides
the JAX_PLATFORMS environment variable at interpreter start; any CLI that
should honor an explicit ``JAX_PLATFORMS=...`` (e.g. CPU smoke runs while the
TPU is held by another process) must re-assert it at the config level before
the first backend lookup.
"""

from __future__ import annotations

import os

import jax


def honor_env_platforms() -> None:
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def host_cpu_tag() -> str:
    """Host-CPU fingerprint for persistent compile-cache dirs.

    XLA:CPU AOT cache entries bake in the compile machine's feature set;
    loading one on a different VM generation is a documented SIGILL/SIGSEGV
    path.  Keying cache dirs on a hash of the cpuinfo flags line makes
    cross-host reuse impossible (bench.py and tests/conftest.py share this
    single definition so their cache keys can never drift apart).
    """
    import hashlib

    try:
        with open("/proc/cpuinfo") as fh:
            # x86 calls the line "flags"; ARM64 calls it "Features" — the
            # guard must key on actual CPU capabilities on both, not fall
            # through to a kernel string that two different-feature VMs
            # can share.
            line = next(
                l for l in fh
                if l.startswith("flags") or l.startswith("Features")
            )
    except (OSError, StopIteration):
        import platform as _platform

        line = _platform.platform()
    return hashlib.md5(line.encode()).hexdigest()[:8]
