"""Platform selection helper.

This image's sitecustomize force-registers the axon TPU plugin and overrides
the JAX_PLATFORMS environment variable at interpreter start; any CLI that
should honor an explicit ``JAX_PLATFORMS=...`` (e.g. CPU smoke runs while the
TPU is held by another process) must re-assert it at the config level before
the first backend lookup.
"""

from __future__ import annotations

import os

import jax


def honor_env_platforms() -> None:
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
