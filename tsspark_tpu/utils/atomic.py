"""THE atomic write-temp-then-rename helper for every durable artifact.

Every file another process may concurrently read — chunk/prep results,
sentinels, checkpoints, run configs — must be written through one of
these helpers: write the full payload to a dot-prefixed temp file in the
TARGET directory (same filesystem, so the rename is atomic; the dot
prefix keeps a torn temp out of every resume/eval glob), then
``os.replace`` it into place.  A reader can then never observe a
half-written artifact: it sees the old file, the new file, or no file.

The static file-protocol race checker (``tsspark_tpu.analysis.fileproto``)
enforces this: any ``open(..., "w")`` / ``np.save*`` / ``json.dump`` that
targets a protocol artifact outside this module (or an allowlisted
append-only log) is a finding.
"""

from __future__ import annotations

import os
from typing import Callable


def _tmp_path(path: str) -> str:
    """Dot-prefixed sibling temp name, unique per writer process.

    Same directory as the target (``os.replace`` must not cross
    filesystems); the pid suffix keeps two processes racing the same
    artifact from clobbering each other's half-written temp — each
    finishes its own and the LAST rename wins whole."""
    d, base = os.path.split(os.path.abspath(path))
    return os.path.join(d, f".{base}.tmp.{os.getpid()}")


def atomic_write(path: str, write_fn: Callable, mode: str = "wb") -> None:
    """Write ``path`` atomically: ``write_fn(fh)`` fills a temp file
    which is closed and renamed into place.

    ``write_fn`` receives the open file object — ``np.save(fh, a)``,
    ``np.savez(fh, **arrays)``, ``json.dump(obj, fh)``, ``pickle.dump``
    and plain ``fh.write`` all accept one, so every artifact format in
    the package rides this single helper.
    """
    tmp = _tmp_path(path)
    try:
        with open(tmp, mode) as fh:
            write_fn(fh)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str) -> None:
    """Atomic text-file write (sentinels, fingerprints, heartbeats)."""
    atomic_write(path, lambda fh: fh.write(text), mode="w")


def append_line(path: str, line: str) -> None:
    """Crash-safe append of ONE line to a shared JSONL log.

    The whole line (newline included) goes down in a single
    ``os.write`` on an ``O_APPEND`` descriptor: POSIX makes each such
    write land at the then-current end of file, so concurrent writer
    PROCESSES (the span log is appended by the orchestrate parent, its
    fit workers, and the serving engine at once) never interleave bytes
    mid-line.  A writer killed between lines leaves a valid file; a
    writer killed mid-write can tear at most its own last line, which
    every reader of these logs already tolerates (same contract as
    ``times.jsonl``)."""
    data = (line if line.endswith("\n") else line + "\n").encode()
    fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    try:
        os.write(fd, data)
    finally:
        os.close(fd)


# A live writer keeps its temp's mtime moving (np.savez streams to the
# fd); 10 minutes of silence means the writer is dead — far beyond the
# orchestrator's stall watchdog, which kills a worker after ~90-270 s
# without progress.
STALE_TEMP_S = 600.0


def sweep_stale_temps(dirpath: str, max_age_s: float = STALE_TEMP_S,
                      recursive: bool = False) -> int:
    """Remove dead writers' orphaned ``.*.tmp.<pid>`` files.

    The pid suffix keeps concurrent writers off each other's temps, but
    it also means a SIGKILLed writer (the stall watchdog's move) leaves
    a uniquely-named orphan no retry ever overwrites — without this
    sweep a crash-looping run grows its scratch dir without bound.
    Age-gated so a racing LIVE writer's in-progress temp is never
    yanked out from under its ``os.replace``.  Returns the count
    removed.

    ``recursive`` walks subdirectories too — the serve registry keeps
    one directory per published version, and a publisher killed
    mid-snapshot orphans its temp INSIDE a version dir where the flat
    sweep never looked (``ParamRegistry`` sweeps its root this way at
    attach time)."""
    import time

    removed = 0
    if recursive:
        listing = ((d, names) for d, _sub, names in os.walk(dirpath))
    else:
        try:
            listing = [(dirpath, os.listdir(dirpath))]
        except OSError:
            return 0
    now = time.time()
    for d, names in listing:
        for name in names:
            if not (name.startswith(".") and ".tmp." in name):
                continue
            p = os.path.join(d, name)
            try:
                if now - os.path.getmtime(p) > max_age_s:
                    os.remove(p)
                    removed += 1
            except OSError:
                continue  # already gone / racing writer won its rename
    return removed
