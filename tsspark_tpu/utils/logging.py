"""Structured logging: one JSON object per line, stdlib-logging compatible.

The Spark reference gets structured executor logs from log4j; here a single
process logs fit/refit/bench events as JSON lines so they are grep- and
pandas-loadable.  Usage:

    log = get_logger("tsspark.fit")
    log.info("fit_done", n_series=30490, fit_seconds=42.1)
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Optional


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "event": record.getMessage(),
        }
        extra = getattr(record, "fields", None)
        if extra:
            payload.update(extra)
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload, default=str)


class StructuredLogger:
    """Thin wrapper adding keyword fields to stdlib logging.

    When an observability trace is active (tsspark_tpu.obs), every
    event is stamped with the current ``trace_id``/``span_id`` — log
    lines then grep-join against the run's span ledger for free.
    """

    def __init__(self, logger: logging.Logger):
        self._logger = logger

    def _log(self, level: int, event: str, **fields: Any) -> None:
        from tsspark_tpu.obs import context as _obs

        ids = _obs.current_ids()
        if ids is not None:
            fields = {**ids, **fields}
        self._logger.log(level, event, extra={"fields": fields})

    def debug(self, event: str, **fields: Any) -> None:
        self._log(logging.DEBUG, event, **fields)

    def info(self, event: str, **fields: Any) -> None:
        self._log(logging.INFO, event, **fields)

    def warning(self, event: str, **fields: Any) -> None:
        self._log(logging.WARNING, event, **fields)

    def error(self, event: str, **fields: Any) -> None:
        self._log(logging.ERROR, event, **fields)


class _DynamicStderrHandler(logging.StreamHandler):
    """Resolves sys.stderr at emit time (plays well with capture/redirect)."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr

    @stream.setter
    def stream(self, value):  # StreamHandler.__init__ compat; ignored
        pass


_CONFIGURED = False


def get_logger(name: str = "tsspark", level: Optional[int] = None
               ) -> StructuredLogger:
    global _CONFIGURED
    root = logging.getLogger("tsspark")
    if not _CONFIGURED:
        handler = _DynamicStderrHandler()
        handler.setFormatter(_JsonFormatter())
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _CONFIGURED = True
    logger = logging.getLogger(name)
    if level is not None:
        logger.setLevel(level)
    return StructuredLogger(logger)


class timed:
    """Context manager: logs wall-clock of a block as a structured event.

    Durations come off ``time.monotonic`` — an NTP step or operator
    clock adjustment mid-block must not produce a negative (or wildly
    inflated) ``seconds`` field."""

    def __init__(self, log: StructuredLogger, event: str, **fields: Any):
        self.log, self.event, self.fields = log, event, fields

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, *_):
        self.fields["seconds"] = round(time.monotonic() - self.t0, 4)
        if exc_type is not None:
            self.fields["failed"] = True
        self.log.info(self.event, **self.fields)
        return False
