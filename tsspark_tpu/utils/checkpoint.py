"""Checkpoint/resume for fitted states and the streaming parameter store.

The reference's streaming eval config warm-starts refits "from prior params"
(BASELINE.json:11), which requires durable fitted-parameter storage.  Format:
one ``.npz`` with the array leaves + one sidecar ``.json`` with the config
fingerprint and series ids, so a resume can verify it is warm-starting into
a compatible model (same param layout) and map rows by series id.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet.design import ScalingMeta
from tsspark_tpu.models.prophet.model import FitState
from tsspark_tpu.resilience import integrity
from tsspark_tpu.io import atomic_write


def config_fingerprint(config: ProphetConfig) -> str:
    """Stable hash of everything that determines the parameter layout."""
    payload = json.dumps(dataclasses.asdict(config), sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _base(path: str) -> str:
    return path[:-4] if path.endswith(".npz") else path


def save_state(
    path: str,
    state: FitState,
    config: ProphetConfig,
    series_ids: Optional[np.ndarray] = None,
    extras: Optional[Dict[str, np.ndarray]] = None,
) -> None:
    """Write a FitState to ``<base>.npz`` + ``<base>.json`` sidecar.

    ``extras``: side arrays that ride the same npz under ``extra_``-
    prefixed keys (e.g. the streaming store's per-series cadence).
    ``load_state`` ignores them — they are not part of the FitState
    contract; consumers read them back with :func:`load_extras`.
    """
    path = _base(path)
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    arrays = {
        "theta": state.theta,
        "loss": state.loss,
        "grad_norm": state.grad_norm,
        "converged": state.converged,
        "n_iters": state.n_iters,
    }
    if state.status is not None:
        arrays["status"] = state.status
    arrays.update(
        {f"meta_{k}": v for k, v in state.meta._asdict().items()}
    )
    arrays.update(
        {f"extra_{k}": v for k, v in (extras or {}).items()}
    )
    # Atomic npz + json (utils.atomic): a reader — a concurrent predict
    # process, a resumed streaming driver — must never np.load a torn
    # checkpoint or parse a half-written sidecar.  The payload CRC stamp
    # (resilience.integrity, same as chunk/prep files) additionally lets
    # readers detect SILENT corruption — the serve registry refuses a
    # mismatching active snapshot and falls back to the last good
    # version instead of serving garbage.
    host = {k: np.asarray(v) for k, v in arrays.items()}
    host = integrity.stamp(host)
    atomic_write(path + ".npz", lambda fh: np.savez(fh, **host))
    if series_ids is None:
        sidecar_ids = None
    else:
        # C-level id stringification: the former per-element
        # ``[str(s) for s in ids]`` was an O(n_series) interpreter pass
        # on every registry publish (ROADMAP item 2).
        ids_arr = np.asarray(series_ids)
        if ids_arr.ndim == 0:  # sized-less iterable: materialize
            ids_arr = np.asarray(list(series_ids))
        if ids_arr.dtype.kind != "U":
            ids_arr = ids_arr.astype(np.str_)
        sidecar_ids = ids_arr.tolist()
    sidecar = {
        "fingerprint": config_fingerprint(config),
        "n_series": int(state.theta.shape[0]),
        "series_ids": sidecar_ids,
        "format": 1,
    }
    atomic_write(path + ".json", lambda fh: json.dump(sidecar, fh),
                 mode="w")


def save_forecaster(path: str, fc) -> None:
    """Persist a fitted Forecaster (state + config + frame context).

    Everything needed for ``load_forecaster(path).predict(...)`` in a fresh
    process: the FitState arrays, the model config, holiday calendars, and
    the pandas-front-end context (column names, training grid, datetime
    flag).  The CLI's ``fit`` -> ``predict`` round trip rides on this.
    """
    from tsspark_tpu.frame import Forecaster  # local: avoid import cycle

    if not isinstance(fc, Forecaster) or fc.state is None:
        raise ValueError("save_forecaster needs a fitted Forecaster")
    path = _base(path)
    save_state(path, fc.state, fc.config, series_ids=fc.series_ids)
    if fc.mcmc_state is not None:
        # Full-posterior fits must survive the round trip, or a reloaded
        # model silently downgrades to narrower MAP intervals.  The draws
        # dominate the file size — that is the cost of the mcmc_samples
        # choice, same as upstream Prophet's serialized Stan draws.
        z = dict(np.load(path + ".npz"))
        z.pop(integrity.INTEGRITY_KEY, None)  # re-stamp over the new set
        z.update(
            mcmc_samples=np.asarray(fc.mcmc_state.samples),
            mcmc_accept_rate=np.asarray(fc.mcmc_state.accept_rate),
            mcmc_step_size=np.asarray(fc.mcmc_state.step_size),
            mcmc_divergences=np.asarray(fc.mcmc_state.divergences),
        )
        z = integrity.stamp(z)
        atomic_write(path + ".npz", lambda fh: np.savez(fh, **z))
    with open(path + ".json") as f:
        sidecar = json.load(f)
    # The model config is stored without holidays' auto-added regressor
    # columns duplicated: fc.config already includes them, and the holiday
    # calendars themselves are stored to rebuild indicator features.
    sidecar["forecaster"] = {
        "mcmc_config": None if fc.mcmc_config is None
            else dataclasses.asdict(fc.mcmc_config),
        "config": dataclasses.asdict(fc.config),
        "backend": fc.backend.name,
        "id_col": fc.id_col, "ds_col": fc.ds_col, "y_col": fc.y_col,
        "cap_col": fc.cap_col, "floor_col": fc.floor_col,
        "regressor_cols": list(fc.regressor_cols),
        "holidays": [dataclasses.asdict(h) for h in fc.holidays],
        "was_datetime": fc._was_datetime,
        "train_ds": None if fc._train_ds is None else
            [float(v) for v in fc._train_ds],
        "freq_days": fc._freq_days,
        "solver_config": dataclasses.asdict(fc.backend.solver_config),
    }
    atomic_write(path + ".json", lambda fh: json.dump(sidecar, fh),
                 mode="w")


def _config_from_dict(d: Dict) -> ProphetConfig:
    from tsspark_tpu.config import RegressorConfig, SeasonalityConfig

    d = dict(d)
    d["seasonalities"] = tuple(
        SeasonalityConfig(**s) for s in d.get("seasonalities", ())
    )
    d["regressors"] = tuple(
        RegressorConfig(**r) for r in d.get("regressors", ())
    )
    return ProphetConfig(**d)


def load_forecaster(path: str):
    """Rebuild a fitted Forecaster saved by :func:`save_forecaster`."""
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.frame import Forecaster
    from tsspark_tpu.models.holidays import Holiday

    path = _base(path)
    with open(path + ".json") as f:
        sidecar = json.load(f)
    ctx = sidecar.get("forecaster")
    if ctx is None:
        raise ValueError(
            f"{path}.json has no forecaster context (state-only checkpoint; "
            "use load_state)"
        )
    config = _config_from_dict(ctx["config"])
    holidays = tuple(
        Holiday(**{**h, "dates": tuple(h["dates"])}) for h in ctx["holidays"]
    )
    # Holiday regressor columns are already part of the stored config;
    # constructing with holidays would re-append them, so attach afterwards.
    fc = Forecaster(
        config,
        solver_config=SolverConfig(**ctx["solver_config"]),
        backend=ctx["backend"],
        id_col=ctx["id_col"], ds_col=ctx["ds_col"], y_col=ctx["y_col"],
        cap_col=ctx["cap_col"], floor_col=ctx["floor_col"],
        regressor_cols=tuple(ctx["regressor_cols"]),
    )
    fc.holidays = holidays
    state, ids = load_state(path, config)
    fc.state = state
    fc.series_ids = ids
    fc._was_datetime = ctx["was_datetime"]
    fc._train_ds = None if ctx["train_ds"] is None else np.asarray(
        ctx["train_ds"], np.float64
    )
    fc._freq_days = ctx["freq_days"]
    z = np.load(path + ".npz")
    if "mcmc_samples" in z.files:
        from tsspark_tpu.config import McmcConfig
        from tsspark_tpu.models.prophet.model import McmcState
        from tsspark_tpu.ops import hmc

        # Convergence diagnostics are a pure function of the draws — cheaper
        # to recompute on load than to version in the checkpoint format.
        rhat, ess = hmc.split_rhat_ess(z["mcmc_samples"])
        fc.mcmc_state = McmcState(
            samples=jnp.asarray(z["mcmc_samples"]),
            meta=state.meta,
            accept_rate=jnp.asarray(z["mcmc_accept_rate"]),
            step_size=jnp.asarray(z["mcmc_step_size"]),
            divergences=jnp.asarray(z["mcmc_divergences"]),
            map_state=state,
            rhat=rhat,
            ess=ess,
        )
        if ctx.get("mcmc_config"):
            fc.mcmc_config = McmcConfig(**ctx["mcmc_config"])
    return fc


def load_extras(path: str) -> Dict[str, np.ndarray]:
    """The ``extras`` arrays a checkpoint was saved with (may be empty)."""
    path = _base(path)
    z = np.load(path + ".npz")
    return {
        k[len("extra_"):]: np.asarray(z[k])
        for k in z.files if k.startswith("extra_")
    }


def load_state(
    path: str, config: ProphetConfig, strict: bool = True,
    return_extras: bool = False,
):
    """Load a FitState; verifies the config fingerprint when ``strict``.

    Returns ``(state, series_ids)``, or ``(state, series_ids, extras)``
    with ``return_extras`` — the latter reads the npz once instead of
    making a large snapshot pay a second full parse via
    :func:`load_extras` (the serve registry's version-flip path).
    """
    path = _base(path)
    with open(path + ".json") as f:
        sidecar = json.load(f)
    if strict and sidecar["fingerprint"] != config_fingerprint(config):
        raise ValueError(
            "checkpoint was written with a different model config "
            f"(fingerprint {sidecar['fingerprint']}); pass strict=False to "
            "force-load"
        )
    z = np.load(path + ".npz")
    # Meta stays HOST numpy float64 (see ScalingMeta): jnp.asarray would
    # downcast ds_start/ds_span to f32 and quantize sub-daily warm starts.
    fields = {
        k[len("meta_"):]: np.asarray(z[k], np.float64)
        for k in z.files if k.startswith("meta_")
    }
    if "changepoints" not in fields:
        # Checkpoint predates per-series changepoint grids in ScalingMeta.
        # Uniform placement (the only placement that existed then) is exactly
        # reconstructible from the config.
        from tsspark_tpu.models.prophet import trend as trend_mod

        b = fields["y_scale"].shape[0]
        fields["changepoints"] = np.asarray(trend_mod.uniform_changepoints(
            np.zeros((b,)), np.ones((b,)),
            config.n_changepoints, config.changepoint_range,
        ))
    meta = ScalingMeta(**fields)
    state = FitState(
        theta=jnp.asarray(z["theta"]),
        meta=meta,
        loss=jnp.asarray(z["loss"]),
        grad_norm=jnp.asarray(z["grad_norm"]),
        converged=jnp.asarray(z["converged"]),
        n_iters=jnp.asarray(z["n_iters"]),
        status=jnp.asarray(z["status"]) if "status" in z.files else None,
    )
    ids = sidecar.get("series_ids")
    ids = None if ids is None else np.asarray(ids)
    if return_extras:
        extras = {
            k[len("extra_"):]: np.asarray(z[k])
            for k in z.files if k.startswith("extra_")
        }
        return state, ids, extras
    return state, ids
