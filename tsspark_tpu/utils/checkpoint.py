"""Checkpoint/resume for fitted states and the streaming parameter store.

The reference's streaming eval config warm-starts refits "from prior params"
(BASELINE.json:11), which requires durable fitted-parameter storage.  Format:
one ``.npz`` with the array leaves + one sidecar ``.json`` with the config
fingerprint and series ids, so a resume can verify it is warm-starting into
a compatible model (same param layout) and map rows by series id.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet.design import ScalingMeta
from tsspark_tpu.models.prophet.model import FitState


def config_fingerprint(config: ProphetConfig) -> str:
    """Stable hash of everything that determines the parameter layout."""
    payload = json.dumps(dataclasses.asdict(config), sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _base(path: str) -> str:
    return path[:-4] if path.endswith(".npz") else path


def save_state(
    path: str,
    state: FitState,
    config: ProphetConfig,
    series_ids: Optional[np.ndarray] = None,
) -> None:
    """Write a FitState to ``<base>.npz`` + ``<base>.json`` sidecar."""
    path = _base(path)
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    arrays = {
        "theta": state.theta,
        "loss": state.loss,
        "grad_norm": state.grad_norm,
        "converged": state.converged,
        "n_iters": state.n_iters,
    }
    arrays.update(
        {f"meta_{k}": v for k, v in state.meta._asdict().items()}
    )
    np.savez(path + ".npz", **{k: np.asarray(v) for k, v in arrays.items()})
    sidecar = {
        "fingerprint": config_fingerprint(config),
        "n_series": int(state.theta.shape[0]),
        "series_ids": None if series_ids is None else [str(s) for s in series_ids],
        "format": 1,
    }
    with open(path + ".json", "w") as f:
        json.dump(sidecar, f)


def load_state(
    path: str, config: ProphetConfig, strict: bool = True
) -> Tuple[FitState, Optional[np.ndarray]]:
    """Load a FitState; verifies the config fingerprint when ``strict``."""
    path = _base(path)
    with open(path + ".json") as f:
        sidecar = json.load(f)
    if strict and sidecar["fingerprint"] != config_fingerprint(config):
        raise ValueError(
            "checkpoint was written with a different model config "
            f"(fingerprint {sidecar['fingerprint']}); pass strict=False to "
            "force-load"
        )
    z = np.load(path + ".npz")
    meta = ScalingMeta(**{
        k[len("meta_"):]: jnp.asarray(z[k])
        for k in z.files if k.startswith("meta_")
    })
    state = FitState(
        theta=jnp.asarray(z["theta"]),
        meta=meta,
        loss=jnp.asarray(z["loss"]),
        grad_norm=jnp.asarray(z["grad_norm"]),
        converged=jnp.asarray(z["converged"]),
        n_iters=jnp.asarray(z["n_iters"]),
    )
    ids = sidecar.get("series_ids")
    return state, None if ids is None else np.asarray(ids)
