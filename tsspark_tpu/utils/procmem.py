"""Process-memory introspection for the one-physical-copy accounting.

The snapshot plane's whole claim is that N serving processes map ONE
page-cache copy of the active version instead of N private heaps —
plain ``VmRSS`` cannot show that (shared file-backed pages count fully
in every mapper's RSS), so the scale ladder and ``ReplicaPool.stats``
read the kernel's sharing-aware counters instead:

* ``RssAnon`` (``/proc/<pid>/status``) — private anonymous heap: where
  an npz snapshot lives, per process;
* ``Pss`` (``/proc/<pid>/smaps_rollup``) — proportional set size:
  shared pages divided by their mapper count, so the pool-wide sum
  counts each physical page once;
* per-mapping ``Rss``/``Pss`` filtered by path fragment
  (``/proc/<pid>/smaps``) — the resident cost attributable to the
  snapshot plane's ``snapcol_`` mappings specifically.

Device-free and dependency-free (reads procfs only); every reader
degrades to ``None`` fields off-Linux.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


def _kb_fields(path: str, wanted) -> Dict[str, float]:
    out: Dict[str, float] = {}
    try:
        with open(path) as fh:
            for line in fh:
                key = line.split(":", 1)[0]
                if key in wanted:
                    out[key] = float(line.split()[1]) / 1024.0  # kB->MB
    except (OSError, ValueError, IndexError):
        pass
    return out


def proc_mem(pid: Optional[int] = None) -> Dict[str, Optional[float]]:
    """{"rss_mb", "rss_anon_mb", "rss_file_mb", "pss_mb"} for ``pid``
    (default: this process), in MB; missing counters are None."""
    pid = os.getpid() if pid is None else int(pid)
    status = _kb_fields(f"/proc/{pid}/status",
                        ("VmRSS", "RssAnon", "RssFile"))
    rollup = _kb_fields(f"/proc/{pid}/smaps_rollup", ("Pss",))
    return {
        "rss_mb": status.get("VmRSS"),
        "rss_anon_mb": status.get("RssAnon"),
        "rss_file_mb": status.get("RssFile"),
        "pss_mb": rollup.get("Pss"),
    }


def mapped_file_mem(pid: Optional[int] = None,
                    marker: str = "snapcol_"
                    ) -> Dict[str, Optional[float]]:
    """Resident cost of ``pid``'s file mappings whose path contains
    ``marker``: {"rss_mb", "pss_mb", "n_mappings"}.  Summing ``pss_mb``
    across a pool counts every shared physical page exactly once — the
    measured numerator of the snapshot plane's RSS-reduction claim."""
    pid = os.getpid() if pid is None else int(pid)
    rss = pss = 0.0
    n = 0
    seen_any = False
    current_match = False
    try:
        with open(f"/proc/{pid}/smaps") as fh:
            for line in fh:
                if "-" in line.split(" ", 1)[0] and ":" not in \
                        line.split(" ", 1)[0]:
                    # Mapping header line ("<lo>-<hi> perms off dev ...").
                    current_match = marker in line
                    n += current_match
                    continue
                if not current_match:
                    continue
                if line.startswith("Rss:"):
                    rss += float(line.split()[1]) / 1024.0
                    seen_any = True
                elif line.startswith("Pss:"):
                    pss += float(line.split()[1]) / 1024.0
    except (OSError, ValueError, IndexError):
        return {"rss_mb": None, "pss_mb": None, "n_mappings": 0}
    return {
        "rss_mb": round(rss, 3) if seen_any or n == 0 else None,
        "pss_mb": round(pss, 3) if seen_any or n == 0 else None,
        "n_mappings": n,
    }
