"""Profiling hooks: XLA/TPU trace capture + lightweight wall-clock timers.

``trace(logdir)`` wraps ``jax.profiler`` so a fit can be captured and viewed
in TensorBoard's profile plugin (installed on this image) — the TPU-native
replacement for the reference's Spark UI stage timeline.  Timers aggregate
named wall-clock sections (host-side view; device work is in the trace).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: Optional[str] = None) -> Iterator[None]:
    """Capture an XLA profiler trace into ``logdir`` (no-op when None)."""
    if logdir is None:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)


class Timers:
    """Accumulating named wall-clock timers (host side)."""

    def __init__(self) -> None:
        self._total: Dict[str, float] = defaultdict(float)
        self._count: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def section(self, name: str) -> Iterator[None]:
        # Monotonic: a wall-clock adjustment mid-section must not land a
        # negative (or inflated) duration in the aggregate.
        t0 = time.monotonic()
        try:
            yield
        finally:
            self._total[name] += time.monotonic() - t0
            self._count[name] += 1

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            k: {
                "total_s": round(self._total[k], 4),
                "count": self._count[k],
                "mean_s": round(self._total[k] / max(self._count[k], 1), 4),
            }
            for k in sorted(self._total)
        }
