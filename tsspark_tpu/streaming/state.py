"""Parameter store: fitted params + scalings keyed by series id.

Backs the streaming incremental-refit path (eval config 5, BASELINE.json:11):
each micro-batch looks up prior parameters for the series it touches,
warm-starts the solver, and writes the refreshed parameters back.  In-memory
dict with npz persistence via utils.checkpoint; new series simply miss and
fall back to data-driven init.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet.design import ScalingMeta
from tsspark_tpu.models.prophet.model import FitState
from tsspark_tpu.utils import checkpoint as ckpt


class ParamStore:
    """Per-series (theta row, scaling meta row) storage."""

    def __init__(self, config: ProphetConfig):
        self.config = config
        self._theta: Dict[str, np.ndarray] = {}
        self._meta: Dict[str, tuple] = {}

    def __len__(self) -> int:
        return len(self._theta)

    def __contains__(self, series_id: str) -> bool:
        return str(series_id) in self._theta

    def update(self, series_ids: Sequence, state: FitState) -> None:
        theta = np.asarray(state.theta)
        meta_rows = list(zip(*[np.asarray(v) for v in state.meta]))
        for i, sid in enumerate(series_ids):
            self._theta[str(sid)] = theta[i]
            self._meta[str(sid)] = meta_rows[i]

    def lookup(
        self, series_ids: Sequence
    ) -> Tuple[Optional[jnp.ndarray], Optional[ScalingMeta], np.ndarray]:
        """Fetch stored rows for the requested series.

        Returns (theta (B, P), meta, found-mask (B,)).  Rows for unknown
        series are zero-filled and flagged False in the mask; callers blend
        them with a cold init.  Returns (None, None, all-False) when no
        requested series is known.
        """
        ids = [str(s) for s in series_ids]
        found = np.asarray([s in self._theta for s in ids])
        if not found.any():
            return None, None, found
        p = self.config.num_params
        theta = np.zeros((len(ids), p), np.float32)
        n_meta = len(ScalingMeta._fields)
        meta_cols = [[] for _ in range(n_meta)]
        some_meta = next(iter(self._meta.values()))
        for i, sid in enumerate(ids):
            row_meta = self._meta.get(sid)
            if row_meta is None:
                row_meta = tuple(np.zeros_like(m) for m in some_meta)
            else:
                theta[i] = self._theta[sid]
            for j in range(n_meta):
                meta_cols[j].append(row_meta[j])
        meta = ScalingMeta(*[jnp.asarray(np.stack(c)) for c in meta_cols])
        return jnp.asarray(theta), meta, found

    # -- persistence -----------------------------------------------------------

    def save(self, path: str) -> None:
        ids = np.asarray(sorted(self._theta))
        theta = jnp.asarray(np.stack([self._theta[s] for s in ids]))
        meta = ScalingMeta(*[
            jnp.asarray(np.stack([self._meta[s][j] for s in ids]))
            for j in range(len(ScalingMeta._fields))
        ])
        state = FitState(
            theta=theta, meta=meta,
            loss=jnp.zeros(len(ids)), grad_norm=jnp.zeros(len(ids)),
            converged=jnp.ones(len(ids), bool),
            n_iters=jnp.zeros(len(ids), jnp.int32),
        )
        ckpt.save_state(path, state, self.config, series_ids=ids)

    @classmethod
    def load(cls, path: str, config: ProphetConfig, strict: bool = True
             ) -> "ParamStore":
        state, ids = ckpt.load_state(path, config, strict=strict)
        store = cls(config)
        if ids is not None:
            store.update(ids, state)
        return store
