"""Parameter store: fitted params + scalings keyed by series id.

Backs the streaming incremental-refit path (eval config 5, BASELINE.json:11):
each micro-batch looks up prior parameters for the series it touches,
warm-starts the solver, and writes the refreshed parameters back.

Storage is the native ParamTable (tsspark_tpu.native, C++): one micro-batch
update/lookup is two memcpy-bound bulk calls over contiguous float64 rows —
the Python layer only interns string series ids to int64 codes.  Persistence
stays npz via utils.checkpoint (atomic write-temp-then-rename — a driver
checkpointing mid-stream can crash without leaving a torn store behind);
new series simply miss and fall back to data-driven init.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from tsspark_tpu import native
from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet.design import ScalingMeta
from tsspark_tpu.models.prophet.model import FitState
from tsspark_tpu.utils import checkpoint as ckpt


def _meta_dim(config: ProphetConfig) -> int:
    # y_scale, floor, ds_start, ds_span + reg_mean/reg_std (R each).
    # Row layout: y_scale, floor, ds_start, ds_span (4) + reg_mean (R) +
    # reg_std (R) + changepoints (n_cp); see _flatten_meta.
    return 4 + 2 * config.num_regressors + config.n_changepoints


def _flatten_meta(meta: ScalingMeta) -> np.ndarray:
    """(B, meta_dim) float64 row-block from a batched ScalingMeta.

    Float64 end-to-end: ``ds_start`` is in absolute epoch days (~2e4), where
    float32's ulp is ~5 minutes — enough to bias hourly-cadence warm-start
    time alignment (the quantity that matters downstream is the *difference*
    of two such starts, see warmstart.transfer_theta).
    """
    cols = [
        np.asarray(meta.y_scale, np.float64)[:, None],
        np.asarray(meta.floor, np.float64)[:, None],
        np.asarray(meta.ds_start, np.float64)[:, None],
        np.asarray(meta.ds_span, np.float64)[:, None],
        np.asarray(meta.reg_mean, np.float64),
        np.asarray(meta.reg_std, np.float64),
        np.asarray(meta.changepoints, np.float64),
    ]
    return np.concatenate(cols, axis=1)


def _unflatten_meta(rows: np.ndarray, config: ProphetConfig) -> ScalingMeta:
    """Numpy float64 fields on purpose: jnp.asarray would silently downcast
    to float32 (x64 is off) and re-introduce the quantization the store
    avoids.  Consumers doing jnp math cast AFTER the precision-critical
    differences are taken (warmstart.py)."""
    r = config.num_regressors
    return ScalingMeta(
        y_scale=np.asarray(rows[:, 0]),
        floor=np.asarray(rows[:, 1]),
        ds_start=np.asarray(rows[:, 2]),
        ds_span=np.asarray(rows[:, 3]),
        reg_mean=np.asarray(rows[:, 4 : 4 + r]),
        reg_std=np.asarray(rows[:, 4 + r : 4 + 2 * r]),
        changepoints=np.asarray(rows[:, 4 + 2 * r :]),
    )


class ParamStore:
    """Per-series (theta row, scaling meta row, cadence) storage.

    The trailing row column is the series' observed median step in days
    (its cadence), recorded by the streaming driver at update time so
    the forecast read path can build every future grid with one
    vectorized broadcast instead of re-deriving each series' cadence
    from history (N native ``union_grid`` calls per forecast).  Zero
    means "never recorded"; readers substitute the daily default.
    """

    def __init__(self, config: ProphetConfig):
        self.config = config
        # +1: the cadence column (see class docstring).
        self._table = native.ParamTable(
            config.num_params + _meta_dim(config) + 1
        )
        self._code_of: Dict[str, int] = {}
        self._id_of: List[str] = []

    def _codes(self, series_ids: Sequence, intern: bool) -> np.ndarray:
        codes = np.empty(len(series_ids), np.int64)
        for i, sid in enumerate(series_ids):
            s = str(sid)
            c = self._code_of.get(s)
            if c is None:
                if not intern:
                    c = -1  # never stored -> guaranteed miss
                else:
                    c = len(self._id_of)
                    self._code_of[s] = c
                    self._id_of.append(s)
            codes[i] = c
        return codes

    def __len__(self) -> int:
        return len(self._table)

    def __contains__(self, series_id: str) -> bool:
        return str(series_id) in self._code_of

    def update(self, series_ids: Sequence, state: FitState,
               step: Optional[np.ndarray] = None) -> None:
        """Upsert fitted rows.  ``step``: per-series median cadence in
        days (``None`` preserves each series' previously recorded value,
        so callers that never measure cadence don't erase it)."""
        if step is None:
            step = self._raw_steps(series_ids)
        rows = np.concatenate(
            [np.asarray(state.theta, np.float64), _flatten_meta(state.meta),
             np.asarray(step, np.float64)[:, None]],
            axis=1,
        )
        self._table.update(self._codes(series_ids, intern=True), rows)

    def _raw_steps(self, series_ids: Sequence) -> np.ndarray:
        """Stored cadence column as-is (0.0 for unknown/unrecorded)."""
        rows, found = self._table.lookup(self._codes(series_ids,
                                                     intern=False))
        return np.where(found, rows[:, -1], 0.0)

    def lookup_step(self, series_ids: Sequence) -> np.ndarray:
        """Per-series median cadence in days, daily default for series
        whose cadence was never recorded."""
        raw = self._raw_steps(series_ids)
        return np.where(raw > 0, raw, 1.0)

    def lookup(
        self, series_ids: Sequence
    ) -> Tuple[Optional[jnp.ndarray], Optional[ScalingMeta], np.ndarray]:
        """Fetch stored rows for the requested series.

        Returns (theta (B, P), meta, found-mask (B,)).  Rows for unknown
        series are zero-filled and flagged False in the mask; callers blend
        them with a cold init.  Returns (None, None, all-False) when no
        requested series is known.
        """
        rows, found = self._table.lookup(self._codes(series_ids, intern=False))
        if not found.any():
            return None, None, found
        p = self.config.num_params
        m = _meta_dim(self.config)
        return (
            jnp.asarray(rows[:, :p]),
            _unflatten_meta(rows[:, p:p + m], self.config),
            found,
        )

    # -- persistence / publication ---------------------------------------------

    def export_state(self):
        """Every stored series as one id-sorted batch.

        Returns ``(state, ids, step)`` — the synthetic FitState (zero
        diagnostics: the store keeps parameters, not solver history),
        the series ids aligned to its rows, and the raw cadence column.
        Shared by :meth:`save` and :meth:`publish` so the checkpoint and
        the serve registry can never disagree on row layout.
        """
        codes, rows = self._table.export()
        ids = np.asarray([self._id_of[c] for c in codes])
        order = np.argsort(ids)
        ids, rows = ids[order], rows[order]
        p = self.config.num_params
        m = _meta_dim(self.config)
        n = len(ids)
        state = FitState(
            theta=jnp.asarray(rows[:, :p]),
            meta=_unflatten_meta(rows[:, p:p + m], self.config),
            loss=jnp.zeros(n), grad_norm=jnp.zeros(n),
            converged=jnp.ones(n, bool),
            n_iters=jnp.zeros(n, jnp.int32),
        )
        return state, ids, rows[:, -1].copy()

    def save(self, path: str) -> None:
        state, ids, step = self.export_state()
        ckpt.save_state(path, state, self.config, series_ids=ids,
                        extras={"step": step})

    def publish(self, registry, activate: bool = True) -> int:
        """Publish the whole store as one new serve-registry version
        (tsspark_tpu.serve.registry.ParamRegistry) — the streaming-side
        write path into the serving subsystem.  Returns the version."""
        state, ids, step = self.export_state()
        return registry.publish(state, ids, step=np.where(step > 0, step, 1.0),
                                activate=activate)

    @classmethod
    def load(cls, path: str, config: ProphetConfig, strict: bool = True
             ) -> "ParamStore":
        state, ids, extras = ckpt.load_state(path, config, strict=strict,
                                             return_extras=True)
        store = cls(config)
        if ids is not None:
            store.update(ids, state, step=extras.get("step"))
        return store
