"""Micro-batch sources for streaming incremental refit.

The reference consumes Kafka micro-batches (eval config 5, BASELINE.json:11).
This machine has no broker and no kafka client, so the source is an
interface: ``InMemorySource`` drives tests and simulations; ``KafkaSource``
is a dependency-gated adapter with the same contract that activates when a
``kafka-python``-compatible client is importable.

Contract: ``poll()`` returns a long-format DataFrame of NEW observations
(series_id, ds, y [, regressor columns]) or None when nothing is pending.
"""

from __future__ import annotations

import abc
import sys
from typing import Iterable, List, Optional

import pandas as pd

from tsspark_tpu.resilience import faults
from tsspark_tpu.resilience.policy import (
    STREAM_POLL,
    CircuitBreaker,
    RetryPolicy,
)


class MicroBatchSource(abc.ABC):
    """A stream of long-format observation micro-batches."""

    @abc.abstractmethod
    def poll(self) -> Optional[pd.DataFrame]:
        """Next micro-batch, or None if the stream is (currently) dry."""

    def commit(self) -> None:
        """Acknowledge the most recent ``poll``'s batch as durably applied.

        The streaming driver calls this AFTER the refit has landed in the
        parameter store, giving at-least-once delivery: a crash between
        poll and commit replays the batch, and replays are idempotent
        (history appends dedup by (series, ds); the refit recomputes the
        same parameters).  Default no-op for sources with no offsets.
        """

    def __iter__(self):
        while (batch := self.poll()) is not None:
            yield batch


class ResilientSource(MicroBatchSource):
    """Retry wrapper for any source's poll loop.

    A transient poll failure (broker hiccup, network blip, injected
    ``stream_poll`` fault) is retried under a ``RetryPolicy`` with
    backoff instead of killing the streaming driver; a failure that
    outlives the policy's attempt/budget limits re-raises.  ``commit``
    passes through untouched — offsets are only ever acknowledged by the
    driver after a refit lands, so retried polls stay at-least-once.

    ``breaker``: an optional ``CircuitBreaker`` shared across polls —
    once a dead broker has failed it open, the next poll raises
    ``CircuitOpen`` immediately instead of retrying to the policy's
    deadline again (the caller decides whether to back off or abort;
    offsets are untouched either way).
    """

    def __init__(self, source: MicroBatchSource,
                 policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self._source = source
        self._policy = policy or STREAM_POLL
        self._breaker = breaker

    def poll(self) -> Optional[pd.DataFrame]:
        def attempt():
            faults.inject("stream_poll")
            return self._source.poll()

        def log_retry(retry: int, e: BaseException) -> None:
            print(
                f"[streaming] poll failed ({type(e).__name__}: {e}); "
                f"retry {retry + 1}", file=sys.stderr,
            )

        # Delegate to RetryPolicy.call — the ONE retry loop — so every
        # policy knob is honored; a hand-rolled attempts-only loop here
        # silently ignored total_budget_s (a wall-clock budget against a
        # permanently-down broker never fired).
        return self._policy.call(attempt, on_retry=log_retry,
                                 breaker=self._breaker)

    def commit(self) -> None:
        self._source.commit()


class InMemorySource(MicroBatchSource):
    """Replays a pre-built list of micro-batch frames (tests/simulation)."""

    def __init__(self, batches: Iterable[pd.DataFrame]):
        self._batches: List[pd.DataFrame] = list(batches)
        self._pos = 0

    def poll(self) -> Optional[pd.DataFrame]:
        if self._pos >= len(self._batches):
            return None
        out = self._batches[self._pos]
        self._pos += 1
        return out


class KafkaSource(MicroBatchSource):
    """Kafka consumer adapter.

    Messages are expected to be JSON rows {series_id, ds, y, ...}; each
    ``poll`` drains up to ``max_records`` into one micro-batch frame.

    ``consumer`` injects any object with the KafkaConsumer ``poll``
    contract (``poll(timeout_ms=..., max_records=...) ->
    {partition: [records with .value]}``) — how the tests exercise this
    path without a broker, and how alternative clients plug in.  Without
    it, a ``kafka-python``-compatible package must be importable.

    ``retry_policy``: when given, transient consumer-poll errors are
    retried under it (e.g. resilience.policy.STREAM_POLL) before
    propagating.  Default None — no built-in retry, so wrapping the
    source in ``ResilientSource`` (or ``run(poll_policy=...)``) stays
    the ONE retry layer; configuring both would multiply attempts.
    """

    def __init__(self, topic: Optional[str] = None, max_records: int = 10000,
                 consumer=None, retry_policy: Optional[RetryPolicy] = None,
                 **consumer_kwargs):
        self._retry_policy = retry_policy
        if consumer is not None:
            self._consumer = consumer
        else:
            try:
                from kafka import KafkaConsumer  # type: ignore
            except ImportError as e:  # pragma: no cover - no client locally
                raise ImportError(
                    "KafkaSource needs the 'kafka-python' package, which is "
                    "not installed on this machine; pass consumer=, use "
                    "InMemorySource, or implement MicroBatchSource over "
                    "your transport"
                ) from e
            import json as _json

            self._consumer = KafkaConsumer(  # pragma: no cover - no broker
                topic,
                value_deserializer=lambda b: _json.loads(b.decode()),
                **consumer_kwargs,
            )
        self._max_records = max_records

    def poll(self) -> Optional[pd.DataFrame]:
        do_poll = lambda: self._consumer.poll(
            timeout_ms=1000, max_records=self._max_records
        )
        records = (self._retry_policy.call(do_poll)
                   if self._retry_policy is not None else do_poll())
        rows = [msg.value for part in records.values() for msg in part]
        if not rows:
            return None
        return pd.DataFrame(rows)

    def commit(self) -> None:
        """Commit consumer offsets for everything polled so far (the
        driver invokes this only after the refit is durably applied)."""
        commit = getattr(self._consumer, "commit", None)
        if commit is not None:
            commit()


class PlaneReplaySource(MicroBatchSource):
    """Replay a columnar data-plane dataset as a micro-batch stream.

    The streaming driver's load/datagen path, wired to the SAME shared
    cache bench.py and the serve loadgen read (tsspark_tpu.data.plane,
    docs/DATA.md): each ``poll`` slices the next ``window`` timesteps
    across (up to ``max_series``) series out of the dataset's memmap
    columns and emits the observed points as a long frame — no private
    datagen path, no copy until the slice.
    """

    def __init__(self, dataset_dir: Optional[str] = None, *,
                 spec=None, root: Optional[str] = None,
                 window: int = 32, max_series: Optional[int] = None,
                 id_col: str = "series_id", ds_col: str = "ds",
                 y_col: str = "y"):
        import numpy as np

        from tsspark_tpu.data import plane

        if dataset_dir is None:
            if spec is None:
                raise ValueError("pass dataset_dir or spec")
            dataset_dir = plane.ensure(spec, root=root)
        self.dataset_dir = dataset_dir
        self._batch = plane.open_batch(dataset_dir)
        self._np = np
        self._window = int(window)
        self._n = (len(self._batch.series_ids) if max_series is None
                   else min(int(max_series), len(self._batch.series_ids)))
        self._cols = (id_col, ds_col, y_col)
        self._t = 0

    def poll(self) -> Optional[pd.DataFrame]:
        np = self._np
        t_len = self._batch.y.shape[1]
        if self._t >= t_len:
            return None
        lo, hi = self._t, min(self._t + self._window, t_len)
        self._t = hi
        y = np.asarray(self._batch.y[:self._n, lo:hi], np.float64)
        mask = np.asarray(self._batch.mask[:self._n, lo:hi]) > 0
        ds = np.asarray(self._batch.ds[lo:hi], np.float64)
        sid = np.repeat(np.asarray(self._batch.series_ids[:self._n]),
                        hi - lo)
        grid = np.tile(ds, self._n)
        obs_flat = mask.reshape(-1)
        if not obs_flat.any():
            # A fully-masked window (e.g. cold-start onset) still
            # advances the clock; hand back an empty frame contract-
            # compatibly by polling the next window.
            return self.poll()
        id_col, ds_col, y_col = self._cols
        return pd.DataFrame({
            id_col: sid[obs_flat],
            ds_col: grid[obs_flat],
            y_col: y.reshape(-1)[obs_flat],
        })
