"""Micro-batch sources for streaming incremental refit.

The reference consumes Kafka micro-batches (eval config 5, BASELINE.json:11).
This machine has no broker and no kafka client, so the source is an
interface: ``InMemorySource`` drives tests and simulations; ``KafkaSource``
is a dependency-gated adapter with the same contract that activates when a
``kafka-python``-compatible client is importable.

Contract: ``poll()`` returns a long-format DataFrame of NEW observations
(series_id, ds, y [, regressor columns]) or None when nothing is pending.
"""

from __future__ import annotations

import abc
from typing import Iterable, List, Optional

import pandas as pd


class MicroBatchSource(abc.ABC):
    """A stream of long-format observation micro-batches."""

    @abc.abstractmethod
    def poll(self) -> Optional[pd.DataFrame]:
        """Next micro-batch, or None if the stream is (currently) dry."""

    def commit(self) -> None:
        """Acknowledge the most recent ``poll``'s batch as durably applied.

        The streaming driver calls this AFTER the refit has landed in the
        parameter store, giving at-least-once delivery: a crash between
        poll and commit replays the batch, and replays are idempotent
        (history appends dedup by (series, ds); the refit recomputes the
        same parameters).  Default no-op for sources with no offsets.
        """

    def __iter__(self):
        while (batch := self.poll()) is not None:
            yield batch


class InMemorySource(MicroBatchSource):
    """Replays a pre-built list of micro-batch frames (tests/simulation)."""

    def __init__(self, batches: Iterable[pd.DataFrame]):
        self._batches: List[pd.DataFrame] = list(batches)
        self._pos = 0

    def poll(self) -> Optional[pd.DataFrame]:
        if self._pos >= len(self._batches):
            return None
        out = self._batches[self._pos]
        self._pos += 1
        return out


class KafkaSource(MicroBatchSource):
    """Kafka consumer adapter.

    Messages are expected to be JSON rows {series_id, ds, y, ...}; each
    ``poll`` drains up to ``max_records`` into one micro-batch frame.

    ``consumer`` injects any object with the KafkaConsumer ``poll``
    contract (``poll(timeout_ms=..., max_records=...) ->
    {partition: [records with .value]}``) — how the tests exercise this
    path without a broker, and how alternative clients plug in.  Without
    it, a ``kafka-python``-compatible package must be importable.
    """

    def __init__(self, topic: Optional[str] = None, max_records: int = 10000,
                 consumer=None, **consumer_kwargs):
        if consumer is not None:
            self._consumer = consumer
        else:
            try:
                from kafka import KafkaConsumer  # type: ignore
            except ImportError as e:  # pragma: no cover - no client locally
                raise ImportError(
                    "KafkaSource needs the 'kafka-python' package, which is "
                    "not installed on this machine; pass consumer=, use "
                    "InMemorySource, or implement MicroBatchSource over "
                    "your transport"
                ) from e
            import json as _json

            self._consumer = KafkaConsumer(  # pragma: no cover - no broker
                topic,
                value_deserializer=lambda b: _json.loads(b.decode()),
                **consumer_kwargs,
            )
        self._max_records = max_records

    def poll(self) -> Optional[pd.DataFrame]:
        records = self._consumer.poll(timeout_ms=1000,
                                      max_records=self._max_records)
        rows = [msg.value for part in records.values() for msg in part]
        if not rows:
            return None
        return pd.DataFrame(rows)

    def commit(self) -> None:
        """Commit consumer offsets for everything polled so far (the
        driver invokes this only after the refit is durably applied)."""
        commit = getattr(self._consumer, "commit", None)
        if commit is not None:
            commit()
