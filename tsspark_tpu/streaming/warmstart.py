"""Warm-start parameter transfer between refits.

A refit on extended data changes every per-series scaling (y_scale grows with
new extremes, ds_span grows with new timestamps, changepoint grids move), so
yesterday's fitted theta lives in a DIFFERENT parameter space than today's
solver.  Feeding it in raw makes warm starts *worse* than cold init.  This
module maps old parameters into the new space analytically:

  time map:   t_old = a * t_new + b  with a = span_new/span_old,
              b = (start_new - start_old)/span_old
  scale map:  r = y_scale_old / y_scale_new  (+ floor shift for logistic)

  k', delta'  — the piecewise slope curve is resampled: slope_new(s'_j) =
                a*r*slope_old(t_old(s'_j)), delta' = successive differences.
  m'          — r * g_old(b)   (trend value at new t=0, rescaled)
  beta'       — r * beta for additive features; unchanged for multiplicative
                (those are relative to trend and unitless).
  log_sigma'  — log_sigma + log r.

This is exact for the trend between changepoints and for all linear
components; the only approximation is quantizing old changepoints onto the
new grid.  (The reference's warm-start path, BASELINE.json:11, solves the
same problem for its Spark micro-batch refits.)
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet import trend as trend_mod
from tsspark_tpu.models.prophet.design import ScalingMeta
from tsspark_tpu.models.prophet.params import ProphetParams, pack, unpack


def transfer_theta(
    theta_old: jnp.ndarray,
    meta_old: ScalingMeta,
    meta_new: ScalingMeta,
    config: ProphetConfig,
) -> jnp.ndarray:
    """Map (B, P) fitted params from meta_old's space into meta_new's space."""
    p = unpack(theta_old, config)
    # The affine maps in FLOAT64 on host: ds_start is absolute epoch days
    # (~2e4), and start_new - start_old is a catastrophic cancellation in
    # float32 (ulp ~5 min) at sub-daily cadence.  The differences/ratios are
    # O(1), so casting the RESULTS to f32 for the jnp math below is lossless
    # in every way that matters.
    f64 = lambda x: np.asarray(x, np.float64)
    dtype = theta_old.dtype
    # Rows for series the store does NOT know arrive zero-filled
    # (ParamStore.lookup contract): their spans/scales are 0 and every map
    # below would be 0/0.  Callers discard those rows via the lookup's
    # found-mask, so substitute harmless identity scalings instead of
    # letting NaNs flow through (they'd be masked later, but a NaN path
    # that "works by accident" hides genuine bugs — round-2 VERDICT #5).
    span_old = f64(meta_old.ds_span)
    scale_new = f64(meta_new.y_scale)
    known = (span_old > 0) & (f64(meta_old.y_scale) > 0)
    span_old = np.where(known, span_old, 1.0)
    a = jnp.asarray(
        np.where(known, f64(meta_new.ds_span), 1.0) / span_old, dtype
    )[:, None]                                                   # (B, 1)
    b = jnp.asarray(
        np.where(known, f64(meta_new.ds_start) - f64(meta_old.ds_start), 0.0)
        / span_old, dtype
    )[:, None]
    r = jnp.asarray(
        np.where(known, f64(meta_old.y_scale), 1.0)
        / np.where(scale_new > 0, scale_new, 1.0), dtype
    )[:, None]

    batch = theta_old.shape[0]
    # Fit-time changepoint grids from the metas: with quantile placement the
    # grids are data-dependent and differ between the old and new fits (and
    # between series); uniform grids round-trip through this identically.
    s_old = jnp.asarray(meta_old.changepoints, dtype)
    s_new = jnp.asarray(meta_new.changepoints, dtype)

    # Old cumulative slope evaluated at new-grid points mapped to old time.
    # slope_old(t) = k + sum_{j: s_old_j <= t} delta_j.  New time t_new maps
    # to old time a*t_new + b, so the new-window origin evaluates at b (NOT
    # at old t=0 — when the history window slides, changepoints in (0, b)
    # must fold into the new base slope).
    eval_pts = jnp.concatenate(
        [b, a * s_new + b], axis=-1
    )  # (B, n_cp+1): new t=0 and each new changepoint, in old time
    idx = trend_mod.changepoint_index(eval_pts, s_old)
    csum = jnp.concatenate(
        [jnp.zeros((batch, 1), dtype), jnp.cumsum(p.delta, axis=-1)], axis=-1
    )
    slope_old_at = p.k[:, None] + jnp.take_along_axis(csum, idx, axis=-1)
    # Linear trend lives in y-scaled units -> rates pick up r; the logistic
    # rate sits inside sigmoid(k*(t-m)), which is invariant to y rescaling
    # (the cap rescales separately), so only the time scale applies there.
    rate_scale = a if config.growth == "logistic" else a * r
    slope_new_at = rate_scale * slope_old_at  # (B, n_cp+1)

    k_new = slope_new_at[:, 0]
    delta_new = jnp.diff(slope_new_at, axis=-1)

    # Trend value at new t=0 (old time b), rescaled; for logistic the offset
    # parameter m is a time location, which maps affinely instead.
    if config.growth == "logistic":
        # m is the sigmoid midpoint in scaled time: t_old = a t_new + b.
        m_new = (p.m - b[:, 0]) / a[:, 0]
        # Floor shift is absorbed by cap/y rescaling at data-prep time.
    else:
        gsum = jnp.concatenate(
            [jnp.zeros((batch, 1), dtype),
             jnp.cumsum(-s_old * p.delta, axis=-1)], axis=-1
        )
        off_old_at0 = p.m + jnp.take_along_axis(gsum, idx[:, :1], axis=-1)[:, 0]
        g_old_at0 = slope_old_at[:, 0] * b[:, 0] + off_old_at0
        shift = ((meta_old.floor - meta_new.floor) / meta_new.y_scale)
        m_new = r[:, 0] * g_old_at0 + shift

    mult_mask = jnp.asarray(
        [1.0 if m else 0.0 for m in config.feature_modes()], dtype
    )
    beta_new = p.beta * jnp.where(mult_mask > 0, 1.0, r)
    log_sigma_new = p.log_sigma + jnp.log(jnp.maximum(r[:, 0], 1e-30))

    return pack(
        ProphetParams(
            k=k_new,
            m=m_new,
            log_sigma=log_sigma_new,
            delta=delta_new,
            beta=beta_new,
        )
    )
