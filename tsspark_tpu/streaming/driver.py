"""Streaming micro-batch refit driver.

The TPU-native version of eval config 5 (BASELINE.json:11): consume
micro-batches from a source, maintain per-series history windows, and refit
touched series in one batched solve per micro-batch, warm-started from the
parameter store through the warm-start space transfer (warmstart.py).

Per-series history lives in the native ingest engine
(tsspark_tpu.native.HistoryStore, C++ via ctypes): bounded sorted
dedup-append on ingest and threaded padded materialization on refit — the
host-side hot path of the loop.

Flow per micro-batch:
  1. absorb new rows into the native history store (sorted, dedup, bounded)
  2. materialize touched series onto their union grid (collect)
  3. look up stored params -> transfer into the new scaling space -> init
     (cold data-driven init for unseen series)
  4. batched fit with a small iteration budget (fit)
  5. write refreshed params back to the store (scatter)
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd
import jax.numpy as jnp

from tsspark_tpu import native
from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import ProphetConfig, SolverConfig
from tsspark_tpu.obs import context as obs
from tsspark_tpu.obs.metrics import DEFAULT as METRICS
from tsspark_tpu.frame import _days_to_ts, _ds_to_days
from tsspark_tpu.models.prophet.design import prepare_fit_data
from tsspark_tpu.models.prophet.init import initial_theta
from tsspark_tpu.resilience.policy import RetryPolicy
from tsspark_tpu.streaming.source import MicroBatchSource, ResilientSource
from tsspark_tpu.streaming.state import ParamStore
from tsspark_tpu.streaming.warmstart import transfer_theta


def median_steps(grid: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Per-series median observed cadence (days) over one union grid.

    ``y`` is the (B, T) materialized batch with NaN holes; a series'
    cadence is the median gap between ITS observed grid points.  One
    vectorized pass — sorting NaN-masked grid copies pushes the holes to
    the tail so the finite diffs are exactly the per-series gaps — in
    place of the per-series ``union_grid`` loop the forecast path used
    to run.  Rows with fewer than two observations get the daily
    default (1.0).
    """
    y = np.asarray(y)
    obs = np.isfinite(y)
    step = np.ones(y.shape[0])
    rows = np.flatnonzero(obs.sum(axis=1) > 1)
    if rows.size:
        g = np.where(obs[rows], np.asarray(grid, np.float64)[None, :],
                     np.nan)
        # Grid is ascending, so sorting only moves the NaNs to the tail.
        d = np.diff(np.sort(g, axis=1), axis=1)
        step[rows] = np.nanmedian(d, axis=1)
    return step


@dataclass
class RefitStats:
    micro_batches: int = 0
    rows_ingested: int = 0
    series_refit: int = 0
    warm_starts: int = 0
    cold_starts: int = 0
    fit_seconds: float = 0.0
    last_batch_seconds: float = 0.0
    # Per-micro-batch refit wall seconds, in arrival order: the latency
    # distribution is the streaming SLO (eval config 5 records mean/p50/
    # max from it), and a scalar total can't show the warm-path speedup.
    batch_seconds: List[float] = field(default_factory=list)


class StreamingForecaster:
    """Incremental refitter over a micro-batch source."""

    def __init__(
        self,
        config: ProphetConfig = ProphetConfig(),
        solver_config: SolverConfig = SolverConfig(max_iters=40),
        backend: str = "tpu",
        max_history: int = 4096,
        id_col: str = "series_id",
        ds_col: str = "ds",
        y_col: str = "y",
        store: Optional[ParamStore] = None,
        warm_start: bool = True,
        autotune_state: Optional[str] = None,
        engine=None,
        **backend_kwargs,
    ):
        """``warm_start=False`` disables the parameter-store transfer:
        every refit starts from the ridge init as if the series were new.
        Exists for the warm-vs-cold comparison eval config 5 records —
        production streaming always wants the default.

        ``autotune_state``: path to a persisted chunk-autotuner state
        (an orchestrate run's ``autotune.json``).  The driver starts its
        backend at the LEARNED chunk width instead of the static default
        — the streaming loop refits a different touched-series count
        every micro-batch, and the learned width is the one measured
        fastest on this runtime.  An explicit ``chunk_size`` in
        ``backend_kwargs`` wins; a missing/corrupt state file is
        ignored (it is pure cache).

        ``engine``: a serve-side prediction engine
        (tsspark_tpu.serve.PredictionEngine).  When attached,
        :meth:`forecast` routes through it — streaming and serving then
        share ONE batched, cached, deadline-aware read path instead of
        maintaining two.  The engine reads the last PUBLISHED registry
        version, so keep it fresh with :meth:`publish`."""
        if autotune_state is not None and "chunk_size" not in backend_kwargs:
            from tsspark_tpu.perf import load_learned_chunk

            learned = load_learned_chunk(autotune_state)
            if learned:
                backend_kwargs["chunk_size"] = learned
        self.config = config
        self.backend = get_backend(backend, config, solver_config,
                                   **backend_kwargs)
        self.store = store if store is not None else ParamStore(config)
        self.warm_start = warm_start
        self.max_history = max_history
        self.id_col, self.ds_col, self.y_col = id_col, ds_col, y_col
        self._hist = native.HistoryStore(max_history)
        self._code_of: Dict[str, int] = {}
        self._ds_was_datetime = False
        self.engine = engine
        self.stats = RefitStats()

    def attach_engine(self, engine) -> None:
        """Route subsequent :meth:`forecast` calls through ``engine``
        (``None`` detaches and restores the direct store read)."""
        self.engine = engine

    # -- ingestion -------------------------------------------------------------

    def _codes(self, sids) -> np.ndarray:
        out = np.empty(len(sids), np.int64)
        for i, sid in enumerate(sids):
            out[i] = self._code_of.setdefault(str(sid), len(self._code_of))
        return out

    def _absorb(self, batch: pd.DataFrame) -> List[str]:
        if not np.issubdtype(batch[self.ds_col].dtype, np.number):
            self._ds_was_datetime = True
        days = _ds_to_days(batch[self.ds_col])
        sids = batch[self.id_col].astype(str).to_numpy()
        self._hist.append(
            self._codes(sids), days, batch[self.y_col].to_numpy(np.float64)
        )
        self.stats.rows_ingested += len(batch)
        return list(dict.fromkeys(sids))  # unique, input order

    # -- refit -----------------------------------------------------------------

    def process(self, batch: pd.DataFrame) -> None:
        """Ingest one micro-batch and refit every touched series."""
        t0 = time.time()
        touched = self._absorb(batch)
        codes = self._codes(touched)
        grid = self._hist.union_grid(codes)
        y = self._hist.materialize(codes, grid)  # (B, T), NaN holes

        data, meta = prepare_fit_data(
            jnp.asarray(grid), jnp.asarray(y), self.config
        )
        # Cold-start series get the same ridge warm start the batch path
        # uses; warm series are overwritten by the transferred params below.
        theta0 = initial_theta(data, self.config, self.backend.solver_config)
        if self.warm_start:
            old_theta, old_meta, found = self.store.lookup(touched)
            if old_theta is not None:
                warm = transfer_theta(old_theta, old_meta, meta, self.config)
                theta0 = jnp.where(
                    jnp.asarray(found)[:, None], warm, theta0
                )
        else:
            found = np.zeros(len(touched), bool)
        state = self.backend.fit(
            jnp.asarray(grid), jnp.asarray(y), init=theta0
        )
        # Cadence is recorded WITH the refreshed params so the forecast
        # path never re-derives it from history (see median_steps).
        self.store.update(touched, state, step=median_steps(grid, y))

        dt = time.time() - t0
        self.stats.micro_batches += 1
        self.stats.series_refit += len(touched)
        self.stats.warm_starts += int(found.sum())
        self.stats.cold_starts += int((~found).sum())
        self.stats.fit_seconds += dt
        self.stats.last_batch_seconds = dt
        self.stats.batch_seconds.append(dt)
        if obs.active():
            obs.record("stream.batch", t0, dt, rows=int(len(batch)),
                       touched=len(touched), warm=int(found.sum()),
                       cold=int((~found).sum()))
            METRICS.counter("tsspark_stream_batches_total").inc()
            METRICS.counter("tsspark_stream_rows_total").inc(len(batch))
            METRICS.histogram("tsspark_stream_batch_seconds").observe(dt)

    def run(self, source: MicroBatchSource,
            max_batches: Optional[int] = None,
            poll_policy: Optional[RetryPolicy] = None,
            poll_breaker=None) -> RefitStats:
        """Drain the source (or up to ``max_batches``).

        ``poll_policy``: wrap the source so transient poll failures are
        retried with backoff (resilience.policy.RetryPolicy) instead of
        killing the driver mid-stream; commits still happen only after
        a refit lands, so retries preserve at-least-once delivery.
        ``poll_breaker`` (resilience.CircuitBreaker) rides along: a
        broker that keeps failing across polls is shed fast with
        ``CircuitOpen`` instead of re-retrying every poll to its
        deadline."""
        if poll_policy is not None:
            source = ResilientSource(source, poll_policy,
                                     breaker=poll_breaker)
        n = 0
        for batch in source:
            self.process(batch)
            # At-least-once: acknowledge offsets only once the refit has
            # landed in the store (see MicroBatchSource.commit).
            source.commit()
            n += 1
            if max_batches is not None and n >= max_batches:
                break
        return self.stats

    def perf_report(self):
        """The backend's cumulative per-dispatch telemetry
        (tsspark_tpu.perf.PerfReport), or None when the backend carries
        no recorder — pass ``perf=PerfRecorder()`` through the backend
        kwargs to enable it."""
        rec = getattr(self.backend, "perf", None)
        return rec.report() if rec is not None else None

    # -- forecasting out of the store ------------------------------------------

    def publish(self, registry, activate: bool = True) -> int:
        """Publish the current parameter store into a serve registry
        (one new version; see ParamStore.publish)."""
        return self.store.publish(registry, activate=activate)

    def forecast(self, series_ids: Sequence, horizon: int,
                 num_samples: Optional[int] = None) -> pd.DataFrame:
        """Forecast from the latest stored parameters (no refit).

        With an attached serve engine the request rides the shared
        micro-batched read path (coalescing, version-keyed cache,
        deadline admission); otherwise it reads the store directly.
        Either way, unknown series raise ``KeyError`` — but the source
        of truth follows the path: the engine serves the PUBLISHED
        registry snapshot, the direct path this driver's live store.
        """
        ids = [str(s) for s in series_ids]
        if self.engine is not None:
            from tsspark_tpu.serve.engine import UnknownSeries

            try:
                res = self.engine.forecast(
                    ids, horizon,
                    # Same default the direct path's predict applies.
                    num_samples=(self.config.uncertainty_samples
                                 if num_samples is None else num_samples),
                )
            except UnknownSeries as e:
                raise KeyError(
                    f"no fitted params for series: "
                    f"{list(e.missing)[:5]} (registry version "
                    f"{e.version}; publish() to refresh)"
                ) from e
            return self._frame(ids, horizon, res.ds, res.values)
        missing = [s for s in ids if s not in self.store]
        if missing:
            raise KeyError(f"no fitted params for series: {missing[:5]}")
        theta, meta, _ = self.store.lookup(ids)
        from tsspark_tpu.models.prophet.model import FitState

        state = FitState(
            theta=theta, meta=meta,
            loss=jnp.zeros(len(ids)), grad_norm=jnp.zeros(len(ids)),
            converged=jnp.ones(len(ids), bool),
            n_iters=jnp.zeros(len(ids), jnp.int32),
        )
        # Continue each series' own calendar at its observed cadence,
        # recorded at update time (median_steps) — one broadcast, no
        # per-series history scans.
        last = np.asarray(meta.ds_start + meta.ds_span)
        step = self.store.lookup_step(ids)
        grid = last[:, None] + step[:, None] * np.arange(1, horizon + 1)
        # Host float64 grid straight through (the serve engine's feed
        # too): a jnp cast here would quantize absolute epoch days to
        # f32 BEFORE prepare_predict_data's f64 time mapping.
        fc = self.backend.predict(state, grid, num_samples=num_samples)
        return self._frame(ids, horizon, grid, fc)

    def _frame(self, ids, horizon: int, grid, fc) -> pd.DataFrame:
        """Long-frame view of a (B, H) forecast dict (shared by the
        direct and engine-routed read paths)."""
        ds_out = np.asarray(grid).reshape(-1)
        if self._ds_was_datetime:
            ds_out = _days_to_ts(ds_out)
        rows = {
            self.id_col: np.repeat(ids, horizon),
            self.ds_col: ds_out,
        }
        for k, v in fc.items():
            rows[k] = np.asarray(v).reshape(-1)
        return pd.DataFrame(rows)
