"""Quantile forecast columns: intervals as shared mmap pages.

The forecast plane (``serve/fplane.py``) removed the serving read
path's compute dependency for POINT forecasts; this module does the
same for INTERVALS (ROADMAP item 3).  At version-flip time the
publisher computes the full (series x horizon-bucket x quantile) table
and lands it in the version dir under the identical spec-first /
atomic-columns / CRC-sentinel-last protocol —

* ``qplane_spec.json`` — identity record (bucket ladder, quantile set,
  draw count, seed, sampling mode, config fingerprint, NUMERICS_REV),
  written FIRST;
* ``qcol_h<bucket>_q<permille>.npy`` — one plain npy per (horizon
  bucket, quantile): ``(n_series, bucket)`` float32 in data units
  (``q100``/``q500``/``q900`` for the default 80% band + median);
* ``qplaneok.json`` — the CRC sentinel, written LAST.  A torn publish
  fails the sentinel and is REJECTED at attach; interval reads then
  fall back to the compute path — never a wrong band, never an outage.

**Row-local sampling is the parity contract.**  Every cell is produced
by a per-series sampler whose RNG is keyed on ``(seed, global_row)``
alone (``np.random.SeedSequence``, the TPU backend's per-chunk
``SeedSequence((seed, chunk))`` idiom taken to row granularity), with
the row's deterministic components (trend/seasonal split) coming from
the engine's own ``backend.predict(num_samples=0)`` — whose
row-locality the engine-parity contract already pins.  The publisher's
chunked batch compute and the read path's one-row compute fallback
therefore run literally the same per-row function on the same inputs:
plane-served bytes equal fallback-computed bytes bit for bit, with no
batch-shape pinning anywhere.

Two sampling modes, recorded in the spec:

* ``"map"`` — the Prophet MAP predictive recipe
  (``models/prophet/predict.py``): simulated future changepoints +
  observation noise around the MAP theta.  Works from the registry
  alone.
* ``"advi"`` — full parameter uncertainty: theta draws from the
  version's persisted mean-field posterior
  (``uncertainty/advi.py``), each draw contributing one trajectory
  (trend + seasonal recomputed per draw, Prophet's
  ``forecast_from_draws`` shape).  Chosen automatically when the
  posterior artifact is present and the config is eligible
  (no regressors/conditional seasonalities — their future values are
  not in the registry).

Logistic growth is refused (structured event): its trend recompute is
not expressible as the row-local host recipe above, and the compute
path already serves logistic intervals.

Delta versions copy-forward unchanged series' quantile columns —
hardlink when no row in a column changed, else one sequential base
read + scatter of the re-sampled changed rows with per-shard CRC
updates — exactly like the point plane.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tsspark_tpu.io import (
    BackpressureError,
    DiskFullError,
    active_ladder,
    link_or_copy,
)
from tsspark_tpu.obs import context as obs
from tsspark_tpu.plane.protocol import (
    attach_column,
    read_json,
    shard_crcs,
    shard_ranges,
    verify_crcs,
    write_column,
    write_sentinel,
    write_spec,
)
from tsspark_tpu.resilience import faults
from tsspark_tpu.serve.fplane import (
    DEFAULT_HOT_HORIZONS,
    DEFAULT_SHARD_ROWS,
    _PUBLISH_CHUNK,
    _predict_rows,
    bucket_ladder,
    future_grid,
)
from tsspark_tpu.uncertainty import advi as advi_mod

__all__ = [
    "QPLANE_FORMAT", "QPLANE_SPEC", "QPLANE_OK", "QCOL_PREFIX",
    "DEFAULT_QUANTILES", "DEFAULT_DRAWS", "QuantilePlaneError",
    "QPlaneView", "permille", "compute_rows", "write_qplane",
    "write_qplane_delta", "attach", "has_qplane", "verify_qplane",
    "quantile_batch", "quantile_rows", "maybe_publish", "qplane_nbytes",
]

#: Plane format revision (reader refuses unknown revisions).
QPLANE_FORMAT = 1

QPLANE_SPEC = "qplane_spec.json"
QPLANE_OK = "qplaneok.json"
QCOL_PREFIX = "qcol_"

#: Default published quantiles: the 80% band (ProphetConfig's
#: interval_width default) plus the median.
DEFAULT_QUANTILES = (0.1, 0.5, 0.9)

#: Sample paths per series (ProphetConfig.uncertainty_samples default).
DEFAULT_DRAWS = 256

DEFAULT_SEED = 0


class QuantilePlaneError(RuntimeError):
    """Structured quantile-plane failure.  ``reason`` is ``"absent"``
    (serve intervals through compute silently) or ``"corrupt"`` (torn
    publish — the reader must refuse it)."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"{reason}: {detail}")
        self.reason = reason


def permille(q: float) -> int:
    """Quantile -> integer permille column tag (0.1 -> 100)."""
    return int(round(float(q) * 1000))


def _col_name(hb: int, q: float) -> str:
    return f"h{int(hb)}_q{permille(q):03d}"


def _col_path(vdir: str, name: str) -> str:
    return os.path.join(vdir, f"{QCOL_PREFIX}{name}.npy")


def _advi_eligible(config) -> bool:
    """ADVI-mode sampling needs every design input recomputable from
    the future ds grid alone: regressor values and seasonality
    conditions live outside the registry, so their configs stay on
    MAP-mode sampling."""
    if config.growth == "logistic":
        return False
    if config.num_regressors:
        return False
    return not any(s.condition_name for s in config.seasonalities)


# ---------------------------------------------------------------------------
# the row-local sampler (shared by publish and compute fallback)
# ---------------------------------------------------------------------------


def _row_rng(seed: int, global_row: int) -> np.random.Generator:
    """The parity key: one generator per (plane seed, global row) —
    nothing about batching, chunking, or padding can reach the draws."""
    return np.random.default_rng(
        np.random.SeedSequence((int(seed), int(global_row)))
    )


def _row_quantiles_map(det_trend, det_add, det_mult, t, theta_row,
                       y_scale, floor, config, qs, draws, seed,
                       global_row) -> np.ndarray:
    """(Q, T) float32 quantile forecasts in data units for ONE row,
    MAP mode: the ``models/prophet/predict.py`` uncertainty recipe
    (simulated future changepoints + observation noise), mirrored as
    host float32 numpy keyed on ``(seed, global_row)``."""
    rng = _row_rng(seed, global_row)
    scale, fl = float(y_scale), float(floor)
    det = ((np.asarray(det_trend, np.float64) - fl) / scale) \
        .astype(np.float32)
    add = (np.asarray(det_add, np.float64) / scale).astype(np.float32)
    mult = np.asarray(det_mult, np.float32)
    t = np.asarray(t, np.float32)
    t_len = t.shape[0]
    n_cp = config.n_changepoints
    theta_row = np.asarray(theta_row, np.float32)
    sigma = np.float32(np.exp(theta_row[2]))
    delta = theta_row[3:3 + n_cp]

    future = (t > 1.0).astype(np.float32)
    dt = np.diff(t, prepend=t[:1])
    mean_dt = float((dt * future).sum()) / max(float(future.sum()), 1.0)
    cp_prob = np.float32(np.clip(n_cp * mean_dt, 0.0, 1.0))
    lam = np.float32(
        max(float(np.abs(delta).mean()) if n_cp else 0.0, 1e-8)
    )

    s_draws = int(draws)
    u = rng.random((s_draws, t_len), dtype=np.float32)
    ind = (u < cp_prob).astype(np.float32) * future[None]
    lap = rng.laplace(0.0, 1.0, (s_draws, t_len)).astype(np.float32)
    new_delta = ind * lap * lam
    if config.growth == "linear":
        c = np.cumsum(new_delta, axis=-1)
        d = np.cumsum(new_delta * t[None], axis=-1)
        tr = det[None] + t[None] * c - d
    else:  # flat: no trend uncertainty beyond the deterministic path
        tr = np.broadcast_to(det[None], (s_draws, t_len))
    noise = rng.standard_normal((s_draws, t_len),
                                dtype=np.float32) * sigma
    samples = tr * (1.0 + mult[None]) + add[None] + noise
    q = np.quantile(samples, np.asarray(qs, np.float64), axis=0)
    return (q * scale + fl).astype(np.float32)


def _row_quantiles_advi(mu_row, rho_row, s_row, x_season, mult_mask, t,
                        y_scale, floor, config, qs, draws, seed,
                        global_row) -> np.ndarray:
    """(Q, T) float32 quantile forecasts in data units for ONE row,
    ADVI mode: each draw is a theta from the row's mean-field posterior
    with its own trend + seasonal trajectory (``forecast_from_draws``'s
    posterior-predictive shape, row-local host numpy)."""
    rng = _row_rng(seed, global_row)
    scale, fl = float(y_scale), float(floor)
    t = np.asarray(t, np.float32)
    t_len = t.shape[0]
    n_cp = config.n_changepoints
    s_draws = int(draws)

    mu_row = np.asarray(mu_row, np.float32)
    rho_row = np.asarray(rho_row, np.float32)
    z = rng.standard_normal((s_draws, mu_row.shape[0]),
                            dtype=np.float32)
    thetas = mu_row[None] + np.exp(rho_row[None]) * z  # (S, P)
    k, m = thetas[:, 0], thetas[:, 1]
    sigma = np.exp(thetas[:, 2])
    delta = thetas[:, 3:3 + n_cp]
    beta = thetas[:, 3 + n_cp:]

    # Deterministic trend per draw (hinge-basis piecewise linear —
    # trend.piecewise_linear's formula — or flat).
    if config.growth == "linear":
        s_row = np.asarray(s_row, np.float32)
        det = k[:, None] * t[None] + m[:, None]
        if n_cp:
            hinge = np.maximum(t[:, None] - s_row[None, :], 0.0)
            det = det + delta @ hinge.T.astype(np.float32)
    else:
        det = np.broadcast_to(m[:, None], (s_draws, t_len))

    # Simulated future changepoints, per-draw Laplace scale.
    future = (t > 1.0).astype(np.float32)
    dt = np.diff(t, prepend=t[:1])
    mean_dt = float((dt * future).sum()) / max(float(future.sum()), 1.0)
    cp_prob = np.float32(np.clip(n_cp * mean_dt, 0.0, 1.0))
    if n_cp:
        lam = np.maximum(np.abs(delta).mean(-1), 1e-8)  # (S,)
        u = rng.random((s_draws, t_len), dtype=np.float32)
        ind = (u < cp_prob).astype(np.float32) * future[None]
        lap = rng.laplace(0.0, 1.0, (s_draws, t_len)) \
            .astype(np.float32)
        new_delta = ind * lap * lam[:, None].astype(np.float32)
        if config.growth == "linear":
            c = np.cumsum(new_delta, axis=-1)
            d = np.cumsum(new_delta * t[None], axis=-1)
            tr = det + t[None] * c - d
        else:
            tr = det
    else:
        tr = det

    # Seasonal split per draw (additive/multiplicative by mode mask;
    # _advi_eligible guarantees no regressor columns).
    fs = config.num_seasonal_features
    beta_s = beta[:, :fs]
    mm = np.asarray(mult_mask[:fs], np.float32)
    x = np.asarray(x_season, np.float32)  # (T, Fs)
    add = (beta_s * (1.0 - mm)[None]) @ x.T
    mult = (beta_s * mm[None]) @ x.T

    noise = rng.standard_normal((s_draws, t_len), dtype=np.float32) \
        * sigma[:, None].astype(np.float32)
    samples = tr * (1.0 + mult) + add + noise
    q = np.quantile(samples, np.asarray(qs, np.float64), axis=0)
    return (q * scale + fl).astype(np.float32)


def compute_rows(snap, config, backend, idx, hb, *,
                 quantiles: Sequence[float] = DEFAULT_QUANTILES,
                 draws: int = DEFAULT_DRAWS,
                 seed: int = DEFAULT_SEED,
                 posterior=None,
                 chunk: int = _PUBLISH_CHUNK) -> Dict[int, np.ndarray]:
    """Quantile forecasts for snapshot rows ``idx`` at bucket ``hb`` —
    THE compute path, used verbatim by both the publisher (all rows,
    chunked) and the read-path fallback (the few uncovered rows).
    Returns ``{permille: (len(idx), hb) float32}`` in data units.

    ``posterior`` (an :class:`~tsspark_tpu.uncertainty.advi.
    AdviPosterior` over ALL snapshot rows) selects ADVI-mode sampling;
    None means MAP mode.  Bitwise parity between any two calls covering
    a row follows from row-local keying — see the module docstring.
    """
    if config.growth == "logistic":
        raise QuantilePlaneError(
            "absent", "logistic growth has no row-local quantile "
            "recipe; intervals stay on the sampled compute path"
        )
    idx = np.asarray(idx, np.int64)
    hb = int(hb)
    sub, step = snap.take(idx)
    grid = future_grid(sub, step, hb)  # (n, hb) float64
    meta = sub.meta
    ds_start = np.asarray(meta.ds_start, np.float64)
    ds_span = np.asarray(meta.ds_span, np.float64)
    t = ((grid - ds_start[:, None]) / ds_span[:, None]) \
        .astype(np.float32)
    y_scale = np.asarray(meta.y_scale, np.float64)
    floor = np.asarray(meta.floor, np.float64)
    qs = tuple(float(q) for q in quantiles)
    out = np.empty((len(idx), len(qs), hb), np.float32)

    if posterior is not None:
        from tsspark_tpu.models.prophet import seasonality

        mu = np.asarray(posterior.mu, np.float32)
        rho = np.asarray(posterior.rho, np.float32)
        s_cp = np.asarray(meta.changepoints, np.float32)
        x_season = seasonality.seasonal_feature_matrix(
            grid, config.seasonalities
        )  # (n, hb, Fs) host numpy
        mult_mask = np.asarray(
            [1.0 if m else 0.0 for m in config.feature_modes()],
            np.float32,
        )
        t_scaled_cp = s_cp  # fit-time changepoints, already scaled
        for i, row in enumerate(idx):
            out[i] = _row_quantiles_advi(
                mu[row], rho[row], t_scaled_cp[i], x_season[i],
                mult_mask, t[i], y_scale[i], floor[i], config, qs,
                draws, seed, int(row),
            )
    else:
        det = _predict_rows(snap, backend, idx, hb, chunk=chunk)
        theta = np.asarray(sub.theta, np.float32)
        for i, row in enumerate(idx):
            out[i] = _row_quantiles_map(
                det["trend"][i], det["additive"][i],
                det["multiplicative"][i], t[i], theta[i], y_scale[i],
                floor[i], config, qs, draws, seed, int(row),
            )
    return {permille(q): np.ascontiguousarray(out[:, j])
            for j, q in enumerate(qs)}


# ---------------------------------------------------------------------------
# publish
# ---------------------------------------------------------------------------


def write_qplane(vdir: str, snap, config, backend, *,
                 horizons: Sequence[int] = DEFAULT_HOT_HORIZONS,
                 quantiles: Sequence[float] = DEFAULT_QUANTILES,
                 draws: int = DEFAULT_DRAWS,
                 seed: int = DEFAULT_SEED,
                 posterior=None,
                 fingerprint: Optional[str] = None,
                 numerics_rev: Optional[int] = None,
                 shard_rows: int = DEFAULT_SHARD_ROWS,
                 chunk: int = _PUBLISH_CHUNK) -> Dict:
    """Land the full quantile plane for ``snap`` in ``vdir``: spec
    first, columns (each atomic), CRC sentinel LAST.  The
    ``qplane_publish`` fault point is armed per column so the chaos
    harness can kill a publisher mid-plane and prove the sentinel
    rejects the tear.  Returns the spec."""
    n = int(np.asarray(snap.state.theta).shape[0])
    buckets = bucket_ladder(horizons)
    qs = tuple(float(q) for q in quantiles)
    cols: Dict[str, np.ndarray] = {}
    for hb in buckets:
        fresh = compute_rows(
            snap, config, backend, np.arange(n), hb, quantiles=qs,
            draws=draws, seed=seed, posterior=posterior, chunk=chunk,
        )
        for q in qs:
            cols[_col_name(hb, q)] = fresh[permille(q)]
    spec = {
        "format": QPLANE_FORMAT,
        "n_series": n,
        "shard_rows": int(shard_rows),
        "buckets": [int(b) for b in buckets],
        "quantiles": [float(q) for q in qs],
        "draws": int(draws),
        "seed": int(seed),
        "mode": "advi" if posterior is not None else "map",
        "horizons": [int(h) for h in horizons],
        "fingerprint": fingerprint,
        "numerics_rev": numerics_rev,
        "columns": {k: {"dtype": a.dtype.str, "shape": list(a.shape)}
                    for k, a in cols.items()},
    }
    write_spec(os.path.join(vdir, QPLANE_SPEC), spec)
    for name, arr in cols.items():
        faults.inject("qplane_publish")
        write_column(_col_path(vdir, name), arr)
    sentinel = {
        "format": QPLANE_FORMAT,
        "n_series": n,
        "shard_rows": int(shard_rows),
        "unix": round(time.time(), 3),
        "shards": [[lo, hi, shard_crcs(cols, lo, hi)]
                   for lo, hi in shard_ranges(n, shard_rows)],
    }
    write_sentinel(os.path.join(vdir, QPLANE_OK), sentinel)
    return spec


def write_qplane_delta(vdir: str, base_vdir: str, changed_rows,
                       snap, config, backend, *,
                       posterior=None,
                       fingerprint: Optional[str] = None,
                       numerics_rev: Optional[int] = None,
                       base_version: Optional[int] = None) -> Dict:
    """Copy-forward delta publish of the quantile plane, mirroring
    ``fplane.write_plane_delta``: unchanged rows' quantile cells are
    the base plane's bytes (their theta AND their ``(seed, row)`` draw
    key are unchanged, so a recompute would reproduce them exactly —
    the hardlink just skips the work); changed rows are re-sampled
    against the NEW snapshot.  Sampling identity (quantiles, draws,
    seed, mode) is inherited from the base spec — a delta can't
    silently flip the recipe mid-ladder."""
    base_spec = read_json(os.path.join(base_vdir, QPLANE_SPEC))
    base_ok = read_json(os.path.join(base_vdir, QPLANE_OK))
    if base_spec is None or base_ok is None:
        raise QuantilePlaneError(
            "absent", f"{base_vdir}: delta publish needs the base "
            "version's quantile plane (spec + sentinel)"
        )
    n = int(base_spec.get("n_series", -1))
    shard_rows = int(base_spec.get("shard_rows", DEFAULT_SHARD_ROWS))
    buckets = tuple(int(b) for b in base_spec.get("buckets") or ())
    qs = tuple(float(q) for q in base_spec.get("quantiles") or ())
    draws = int(base_spec.get("draws", DEFAULT_DRAWS))
    seed = int(base_spec.get("seed", DEFAULT_SEED))
    if base_spec.get("mode") == "map":
        posterior = None
    elif posterior is None:
        raise QuantilePlaneError(
            "absent", f"{base_vdir}: base plane is ADVI-mode but the "
            "delta version has no posterior — publish full instead"
        )
    changed = np.unique(np.asarray(changed_rows, np.int64))
    if len(changed) and (changed[0] < 0 or changed[-1] >= n):
        raise ValueError(f"changed rows outside [0, {n})")
    fresh: Dict[int, Dict[int, np.ndarray]] = {}
    if len(changed):
        for hb in buckets:
            fresh[hb] = compute_rows(
                snap, config, backend, changed, hb, quantiles=qs,
                draws=draws, seed=seed, posterior=posterior,
            )
    spec = dict(base_spec, fingerprint=fingerprint,
                numerics_rev=numerics_rev,
                delta_from=base_version, n_changed=int(len(changed)))
    write_spec(os.path.join(vdir, QPLANE_SPEC), spec)
    scattered: Dict[str, np.ndarray] = {}
    for name in base_spec["columns"]:
        src = _col_path(base_vdir, name)
        dst = _col_path(vdir, name)
        faults.inject("qplane_publish")
        if not len(changed):
            link_or_copy(src, dst)
            continue
        hb_tag, q_tag = name.split("_", 1)
        base_mm = attach_column(src)
        out = np.array(base_mm)        # copy-forward: one sequential read
        del base_mm
        out[changed] = np.asarray(
            fresh[int(hb_tag[1:])][int(q_tag[1:])], out.dtype
        )
        write_column(dst, out)
        scattered[name] = out
    touched = set(np.unique(changed // shard_rows).tolist())
    shards = []
    for entry in base_ok.get("shards") or ():
        lo, hi, crcs = int(entry[0]), int(entry[1]), dict(entry[2])
        if lo // shard_rows in touched:
            crcs.update(shard_crcs(scattered, lo, hi))
        shards.append([lo, hi, crcs])
    sentinel = dict(base_ok, unix=round(time.time(), 3), shards=shards)
    write_sentinel(os.path.join(vdir, QPLANE_OK), sentinel)
    return spec


# ---------------------------------------------------------------------------
# attach / verify
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QPlaneView:
    """One attached (memmap) quantile plane."""

    n_series: int
    buckets: Tuple[int, ...]
    quantiles: Tuple[float, ...]
    #: bucket -> permille -> (n_series, bucket) read-only memmap.
    columns: Dict[int, Dict[int, np.ndarray]]
    draws: int
    seed: int
    mode: str
    fingerprint: Optional[str]
    numerics_rev: Optional[int]

    def covers(self, hb: int, qs: Sequence[float]) -> bool:
        """Whether every requested quantile at this bucket can be
        gathered from the plane."""
        cols = self.columns.get(int(hb))
        if cols is None:
            return False
        return all(permille(q) in cols for q in qs)


def attach(vdir: str, *, verify: bool = True,
           expected_n: Optional[int] = None) -> QPlaneView:
    """Attach the quantile plane in ``vdir`` as memmap views.

    ``verify`` recomputes every shard CRC against the sentinel before
    any column is trusted.  Raises ``QuantilePlaneError("absent")``
    when no plane was published here, ``("corrupt")`` for anything
    torn, truncated, or mismatched."""
    sentinel = read_json(os.path.join(vdir, QPLANE_OK))
    spec = read_json(os.path.join(vdir, QPLANE_SPEC))
    if sentinel is None and spec is None:
        raise QuantilePlaneError(
            "absent", f"no quantile plane under {vdir}"
        )
    if spec is None or sentinel is None:
        raise QuantilePlaneError(
            "corrupt",
            f"{vdir}: quantile plane is half-published "
            f"(spec={'ok' if spec else 'missing'}, "
            f"sentinel={'ok' if sentinel else 'missing'})",
        )
    if spec.get("format") != QPLANE_FORMAT \
            or sentinel.get("format") != QPLANE_FORMAT:
        raise QuantilePlaneError(
            "corrupt",
            f"{vdir}: quantile plane format {spec.get('format')} != "
            f"{QPLANE_FORMAT}",
        )
    n = int(spec.get("n_series", -1))
    if expected_n is not None and n != int(expected_n):
        raise QuantilePlaneError(
            "corrupt",
            f"{vdir}: quantile plane carries {n} series, snapshot "
            f"says {expected_n}",
        )
    buckets = tuple(int(b) for b in spec.get("buckets") or ())
    qs = tuple(float(q) for q in spec.get("quantiles") or ())
    flat: Dict[str, np.ndarray] = {}
    for name, meta in (spec.get("columns") or {}).items():
        path = _col_path(vdir, name)
        try:
            mm = attach_column(path)
        except Exception as e:
            raise QuantilePlaneError("corrupt", f"{path}: {e}")
        if (mm.dtype.str != meta.get("dtype")
                or list(mm.shape) != meta.get("shape")):
            raise QuantilePlaneError(
                "corrupt",
                f"{path}: on-disk {mm.dtype.str}{list(mm.shape)} != "
                f"spec {meta.get('dtype')}{meta.get('shape')}",
            )
        flat[name] = mm
    for hb in buckets:
        for q in qs:
            if _col_name(hb, q) not in flat:
                raise QuantilePlaneError(
                    "corrupt",
                    f"{vdir}: quantile plane is missing column "
                    f"{_col_name(hb, q)!r}",
                )
    if verify:
        bad = verify_crcs(flat, sentinel.get("shards"))
        if bad is not None:
            name, lo, hi = bad
            raise QuantilePlaneError(
                "corrupt",
                f"{_col_path(vdir, name)}: shard [{lo}, {hi}) CRC "
                "mismatch (torn or silently corrupted quantile column)",
            )
    columns: Dict[int, Dict[int, np.ndarray]] = {
        hb: {permille(q): flat[_col_name(hb, q)] for q in qs}
        for hb in buckets
    }
    return QPlaneView(
        n_series=n, buckets=buckets, quantiles=qs, columns=columns,
        draws=int(spec.get("draws", DEFAULT_DRAWS)),
        seed=int(spec.get("seed", DEFAULT_SEED)),
        mode=str(spec.get("mode", "map")),
        fingerprint=spec.get("fingerprint"),
        numerics_rev=spec.get("numerics_rev"),
    )


def has_qplane(vdir: str) -> bool:
    """Cheap presence probe (no CRC sweep)."""
    return os.path.exists(os.path.join(vdir, QPLANE_OK))


def verify_qplane(vdir: str) -> bool:
    """Deep integrity check: True when the plane attaches AND every
    shard CRC matches (the chaos harness's torn-plane probe)."""
    try:
        attach(vdir, verify=True)
        return True
    except QuantilePlaneError:
        return False


def qplane_nbytes(vdir: str) -> Optional[int]:
    """Total column bytes of the quantile plane in ``vdir``; None when
    no plane is published."""
    spec = read_json(os.path.join(vdir, QPLANE_SPEC))
    if spec is None:
        return None
    total = 0
    for meta in (spec.get("columns") or {}).values():
        n = 1
        for d in meta.get("shape") or ():
            n *= int(d)
        total += n * int(np.dtype(meta["dtype"]).itemsize)
    return total


# ---------------------------------------------------------------------------
# the zero-dispatch read path
# ---------------------------------------------------------------------------


def quantile_batch(view: QPlaneView, snap, idx: np.ndarray,
                   hb: int) -> Tuple[np.ndarray, Dict[int, np.ndarray]]:
    """Serve snapshot rows ``idx`` at bucket ``hb`` straight from the
    quantile plane: one vectorized memmap gather per quantile plus the
    recomputed float64 ``ds`` grid.  Returns ``(grid, gathered)`` with
    ``gathered[permille]`` shaped ``(len(idx), hb)``.

    This is the quantile read root of the ``serve-qplane-read`` effect
    budget (pyproject ``[tool.tsspark.analysis.effects]``): nothing
    reachable from here may dispatch or compile a JAX program, touch
    durable storage, or spawn — page-cache reads and host numpy only.
    The grid math is ``fplane.plane_batch``'s, verbatim."""
    idx = np.asarray(idx, np.int64)
    meta = snap.state.meta
    last = (np.asarray(meta.ds_start, np.float64)[idx]
            + np.asarray(meta.ds_span, np.float64)[idx])
    step = np.asarray(snap.step, np.float64)[idx]
    grid = last[:, None] + step[:, None] * np.arange(1, int(hb) + 1)
    cols = view.columns[int(hb)]
    return grid, {pm: np.asarray(mm[idx]) for pm, mm in cols.items()}


def quantile_rows(view: QPlaneView, snap, idx: np.ndarray,
                  hb: int) -> List[Dict[str, np.ndarray]]:
    """Per-series form of :func:`quantile_batch`: one dict per index
    with ``"ds"`` and one ``"q<permille>"`` array per quantile."""
    grid, gathered = quantile_batch(view, snap, idx, hb)
    out: List[Dict[str, np.ndarray]] = []
    for i in range(len(grid)):
        row: Dict[str, np.ndarray] = {
            f"q{pm:03d}": v[i] for pm, v in gathered.items()
        }
        row["ds"] = grid[i]
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# publish orchestration
# ---------------------------------------------------------------------------


def maybe_publish(registry, version: int, backend=None, *,
                  horizons: Sequence[int] = DEFAULT_HOT_HORIZONS,
                  quantiles: Sequence[float] = DEFAULT_QUANTILES,
                  draws: int = DEFAULT_DRAWS,
                  seed: int = DEFAULT_SEED,
                  force: bool = False) -> Optional[Dict]:
    """Best-effort quantile-plane publish for ``version`` — the flip
    orchestration hook, riding next to ``fplane.maybe_publish``.
    Idempotent; speculative (bows to the disk-pressure ladder and
    degrades to None on a storage refusal); killable
    (``$TSSPARK_QPLANE=0``).

    Mode selection: ADVI when the version dir holds a compatible
    posterior artifact and the config is eligible, else MAP.  Logistic
    growth refuses with a structured event — intervals for logistic
    configs stay on the sampled compute path."""
    if os.environ.get("TSSPARK_QPLANE", "1") == "0":
        return None
    version = int(version)
    vdir = registry.version_dir(version)
    config = registry.config
    if config.growth == "logistic":
        obs.event("qplane.unsupported", version=version,
                  reason="logistic-growth")
        return None
    if has_qplane(vdir) and not force:
        return {"status": "present", "version": version}
    lad = active_ladder(registry.root)
    if lad is not None and not lad.allows("speculate"):
        obs.event("qplane.shed", version=version,
                  state=lad.state(), reason="disk-pressure")
        return None
    if backend is None:
        from tsspark_tpu.backends.registry import get_backend
        from tsspark_tpu.config import SolverConfig

        backend = get_backend("tpu", config, SolverConfig())
    t0 = time.time()
    try:
        snap = registry.load(version, fallback=False)
        n = int(np.asarray(snap.state.theta).shape[0])
        posterior = None
        if _advi_eligible(config):
            loaded = advi_mod.load_posterior(vdir)
            if loaded is not None and loaded[0].mu.shape[0] == n:
                posterior = loaded[0]
        info = None
        try:
            info = registry.delta_info(version)
        except Exception:
            info = None  # torn/racing manifest: publish full
        base_v = None if not info else info.get("base_version")
        base_ok = (base_v is not None
                   and has_qplane(registry.version_dir(int(base_v))))
        if base_ok:
            base_spec = read_json(os.path.join(
                registry.version_dir(int(base_v)), QPLANE_SPEC))
            if (base_spec or {}).get("mode") == "advi" \
                    and posterior is None:
                base_ok = False  # recipe changed: publish full
        if base_ok:
            spec = write_qplane_delta(
                vdir, registry.version_dir(int(base_v)),
                info.get("changed_rows") or (), snap, config, backend,
                posterior=posterior, base_version=int(base_v),
            )
            status = "published-delta"
        else:
            spec = write_qplane(
                vdir, snap, config, backend, horizons=horizons,
                quantiles=quantiles, draws=draws, seed=seed,
                posterior=posterior,
            )
            status = "published"
    except (DiskFullError, BackpressureError) as e:
        obs.event("qplane.refused", version=version, error=repr(e))
        return None
    publish_s = round(time.time() - t0, 3)
    out = {"status": status, "version": version,
           "publish_s": publish_s, "mode": spec.get("mode"),
           "n_series": int(spec.get("n_series", 0)),
           "buckets": list(spec.get("buckets") or ()),
           "quantiles": list(spec.get("quantiles") or ()),
           "nbytes": qplane_nbytes(vdir)}
    obs.event("qplane.published", **out)
    return out
