"""NUTS gold tier: sampled full-posterior audit of the ADVI intervals.

"Going NUTS with ADVI" (PAPERS.md) justifies serving ADVI intervals by
measuring them against NUTS — this module is that measurement wired
into the fleet.  Running full HMC chains (``ops/hmc.py``) over a
million series per version is not a serving cost anyone pays, so the
gold tier samples a **deterministic audit subset** per version
(``SeedSequence((seed, version))`` — every operator who re-runs the
audit for a version sees the same rows) and records the
parameter-space quantile divergence between the two posteriors.

The divergence unit is **NUTS posterior standard deviations**: for each
audited quantile ``q``, parameter ``p`` and series ``b``,

    |Q_nuts(q) - (mu + exp(rho) * z_q)| / sd_nuts

maximized over parameters and quantiles.  ~0.1 sd means the mean-field
fit is indistinguishable from gold at served-interval resolution; a
drift upward across versions is the early-warning signal that the
model family has outgrown the Gaussian approximation.  The report
lands as ``gold_audit.json`` in the version dir (atomic, same identity
header posture as every other published artifact) and flows into
RUNHISTORY through the calibration row family.

NUTS log density includes the ``log_sigma`` change-of-variables
Jacobian (``models/prophet/model.mcmc_core``) while the ADVI objective
is the MAP parameterization without it — a known, deliberate modeling
difference that shows up as a small constant sigma-quantile offset in
the divergence, not a regression signal.
"""

from __future__ import annotations

import json
import os
from statistics import NormalDist
from typing import Optional, Sequence, Tuple

import numpy as np

from tsspark_tpu.config import NUMERICS_REV, McmcConfig
from tsspark_tpu.io import atomic_write
from tsspark_tpu.obs import context as obs

__all__ = [
    "GOLD_FILE",
    "GOLD_FORMAT",
    "DEFAULT_MAX_SERIES",
    "select_rows",
    "quantile_divergence",
    "run_gold",
    "audit_version",
    "load_audit",
]

GOLD_FORMAT = 1
GOLD_FILE = "gold_audit.json"

#: Audit subset size.  Small on purpose: the gold tier exists to detect
#: posterior-family drift, and eight full NUTS chains per version is
#: already ~1e3x the evidence of zero.
DEFAULT_MAX_SERIES = 8
DEFAULT_QUANTILES = (0.1, 0.5, 0.9)


def select_rows(
    n_series: int,
    version: int,
    *,
    max_series: int = DEFAULT_MAX_SERIES,
    seed: int = 0,
) -> np.ndarray:
    """The version's deterministic audit subset (sorted row indices).

    Keyed by ``SeedSequence((seed, version))``: re-running the audit for
    a version always lands on the same rows, and consecutive versions
    rotate coverage across the fleet instead of auditing one lucky
    corner forever.
    """
    if n_series <= max_series:
        return np.arange(n_series, dtype=np.int64)
    rng = np.random.default_rng(
        np.random.SeedSequence((int(seed), int(version)))
    )
    rows = rng.choice(n_series, size=int(max_series), replace=False)
    return np.sort(rows).astype(np.int64)


def quantile_divergence(
    samples,
    mu,
    rho,
    quantiles: Sequence[float] = DEFAULT_QUANTILES,
) -> np.ndarray:
    """ADVI-vs-NUTS quantile divergence per series, (B,).

    For each quantile the NUTS empirical quantile of the (S, B, P)
    draws is compared against the ADVI Gaussian quantile
    ``mu + exp(rho) * z_q``, normalized by the WIDER of the two
    posterior sds — the max over parameters and quantiles is the
    series' divergence in posterior-sd units.  Normalizing by the
    wider sd keeps the metric finite when a short chain collapses on
    a marginal (NUTS sd ~ 0 would otherwise blow the ratio up on a
    sampler artifact rather than a posterior-family failure).
    """
    s = np.asarray(samples, np.float64)
    mu = np.asarray(mu, np.float64)
    sd = np.exp(np.asarray(rho, np.float64))
    scale = np.maximum(np.maximum(s.std(axis=0, ddof=1), sd), 1e-12)
    div = np.zeros(mu.shape[0], np.float64)
    for q in quantiles:
        z = NormalDist().inv_cdf(float(q))
        gap = np.abs(np.quantile(s, float(q), axis=0) - (mu + sd * z))
        div = np.maximum(div, (gap / scale).max(axis=-1))
    return div


def run_gold(
    data,
    theta0,
    config,
    key,
    mcmc_config: Optional[McmcConfig] = None,
) -> Tuple:
    """One batched NUTS run + split diagnostics over the audit subset.

    Thin wrapper over the fleet's existing jitted sampler program
    (``models/prophet/model.mcmc_core`` -> ``ops/hmc.sample``) — the
    gold tier adds no new numerics, only selection and measurement.

    Returns ``(HmcResult, rhat (B, P), ess (B, P))``.
    """
    from tsspark_tpu.models.prophet.model import mcmc_core
    from tsspark_tpu.ops import hmc

    mcmc_config = McmcConfig() if mcmc_config is None else mcmc_config
    res = mcmc_core(data, theta0, key, config, mcmc_config)
    rhat, ess = hmc.split_rhat_ess(res.samples)
    return res, rhat, ess


def audit_version(
    registry,
    data_dir: Optional[str] = None,
    version: Optional[int] = None,
    *,
    arrays: Optional[Tuple] = None,
    max_series: int = DEFAULT_MAX_SERIES,
    seed: int = 0,
    mcmc_config: Optional[McmcConfig] = None,
    quantiles: Sequence[float] = DEFAULT_QUANTILES,
) -> Optional[dict]:
    """Audit one registry version's ADVI posterior against NUTS.

    Loads the version's posterior + snapshot, gathers the audit rows'
    data off the data plane (``data_dir``; or pass ``arrays=(ds, y,
    mask, cap)`` directly — e.g. the holdout-truncated arrays the
    calibration smoke fitted on, so the two posteriors condition on the
    SAME data), runs the gold chains warm-started from the MAP theta,
    and writes ``gold_audit.json`` into the version dir.  Returns the
    report dict, or None when the version has no usable ADVI posterior
    (nothing to audit — the fleet is serving MAP intervals).
    """
    import jax

    from tsspark_tpu.models.prophet.design import prepare_fit_data
    from tsspark_tpu.uncertainty import advi as advi_mod
    from tsspark_tpu.uncertainty.qplane import _advi_eligible

    version = (registry.active_version() if version is None
               else int(version))
    if version is None:
        return None
    vdir = registry.version_dir(int(version))
    loaded = advi_mod.load_posterior(vdir)
    if loaded is None or not _advi_eligible(registry.config):
        obs.event("gold.skipped", version=int(version),
                  reason="no-advi-posterior")
        return None
    post, header = loaded

    snap = registry.load(int(version))
    n = len(snap.series_ids)
    if int(np.asarray(post.mu).shape[0]) != n:
        obs.event("gold.skipped", version=int(version),
                  reason="posterior-shape-mismatch")
        return None
    rows = select_rows(n, int(version), max_series=max_series,
                       seed=seed)

    if arrays is None:
        from tsspark_tpu.data import plane

        batch = plane.open_batch(data_dir)
        ds, y = np.asarray(batch.ds), batch.y
        mask, cap = batch.mask, batch.cap
    else:
        ds, y, mask, cap = arrays
    sub = lambda a: (None if a is None
                     else np.ascontiguousarray(np.asarray(a)[rows]))
    data, _meta = prepare_fit_data(
        np.asarray(ds, np.float64), sub(y), registry.config,
        mask=sub(mask), cap=sub(cap),
    )
    state_sub, _step = snap.take(rows)
    theta0 = np.nan_to_num(np.asarray(state_sub.theta, np.float32))

    mcmc_config = McmcConfig() if mcmc_config is None else mcmc_config
    key = jax.random.fold_in(jax.random.PRNGKey(int(seed)),
                             int(version))
    res, rhat, ess = run_gold(data, theta0, registry.config, key,
                              mcmc_config)
    div = quantile_divergence(
        res.samples, np.asarray(post.mu)[rows],
        np.asarray(post.rho)[rows], quantiles,
    )
    report = {
        "format": GOLD_FORMAT,
        "numerics_rev": NUMERICS_REV,
        "version": int(version),
        "seed": int(seed),
        "posterior_seed": int(header.get("seed", 0)),
        "rows": [int(r) for r in rows],
        "quantiles": [float(q) for q in quantiles],
        "num_warmup": int(mcmc_config.num_warmup),
        "num_samples": int(mcmc_config.num_samples),
        "qdiv": [round(float(d), 6) for d in div],
        "qdiv_max": round(float(div.max()), 6),
        "qdiv_mean": round(float(div.mean()), 6),
        "rhat_max": round(float(np.max(rhat)), 6),
        "ess_min": round(float(np.min(ess)), 3),
        "accept_mean": round(
            float(np.asarray(res.accept_rate).mean()), 6),
        "hmc_divergences": int(np.asarray(res.divergences).sum()),
    }
    atomic_write(
        os.path.join(vdir, GOLD_FILE),
        lambda fh: json.dump(report, fh, indent=1), mode="w",
    )
    obs.event("gold.audit", version=int(version),
              qdiv_max=report["qdiv_max"],
              rhat_max=report["rhat_max"],
              hmc_divergences=report["hmc_divergences"])
    return report


def load_audit(version_dir: str) -> Optional[dict]:
    """The version's gold audit report, or None when absent/unreadable."""
    path = os.path.join(version_dir, GOLD_FILE)
    try:
        with open(path) as fh:
            report = json.load(fh)
    except (OSError, ValueError):
        return None
    if report.get("format") != GOLD_FORMAT:
        return None
    return report
