"""Batched mean-field ADVI over the padded (n_series, T) design tensors.

One jitted program advances every series' variational posterior in
lockstep — the exact execution shape of the L-BFGS MAP solve, with the
per-series ELBO standing in for the per-series posterior value.  The
posterior family is diagonal Gaussian over the flat theta packing
(``params.py``: ``[k, m, log_sigma, delta, beta]``), parameterized as
``(mu, rho)`` with stddev ``exp(rho)`` so the scale stays positive
without a constraint.

The objective per series ``b`` is the negative reparameterized ELBO

    L_b = E_eps[ neg_log_posterior(mu + exp(rho) * eps) ]_b
          - sum_p rho_{b,p}

(the entropy of a diagonal Gaussian is ``sum_p rho + const``; the
constant cannot move the optimum so it is dropped).  The Monte Carlo
expectation uses ``num_elbo_samples`` shared draws per step, keyed by
``fold_in(key, step)`` — fully deterministic under a fixed key.  The
total loss is ``sum_b L_b``: its gradient decouples per series exactly
like the MAP objective, so one Adam step advances all posteriors.

Adam is hand-rolled inside a ``lax.scan`` (the image has no optax and
the update is ten lines); ``mu`` warm-starts at the MAP theta so ADVI
refines an already-converged point rather than re-finding it.
"""

from __future__ import annotations

import io
import json
import os
from typing import NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from tsspark_tpu.config import NUMERICS_REV, AdviConfig, ProphetConfig
from tsspark_tpu.io import atomic_write
from tsspark_tpu.models.prophet.design import FitData
from tsspark_tpu.models.prophet.loss import neg_log_posterior

__all__ = [
    "AdviPosterior",
    "fit_advi",
    "save_posterior",
    "load_posterior",
    "POSTERIOR_FILE",
    "POSTERIOR_FORMAT",
]

POSTERIOR_FORMAT = 1
POSTERIOR_FILE = "advi_posterior.npz"


class AdviPosterior(NamedTuple):
    """Per-series diagonal-Gaussian posterior over the flat theta."""

    mu: jnp.ndarray    # (B, P) posterior mean
    rho: jnp.ndarray   # (B, P) log posterior stddev
    elbo: jnp.ndarray  # (B,)   final per-series ELBO estimate


def _elbo_losses(mu, rho, data, config, eps):
    """Per-series negative ELBO, (B,).  eps: (K, B, P) standard normal."""
    sd = jnp.exp(rho)
    nlps = jax.vmap(
        lambda e: neg_log_posterior(mu + sd * e, data, config)
    )(eps)  # (K, B)
    return nlps.mean(0) - rho.sum(-1)


def _fit_advi(theta0, data, key, config, advi):
    mu0 = jnp.asarray(theta0)
    rho0 = jnp.full_like(mu0, advi.init_rho)
    k_mc, (b, p) = advi.num_elbo_samples, mu0.shape
    dtype = mu0.dtype

    def total(params, eps):
        losses = _elbo_losses(params[0], params[1], data, config, eps)
        return losses.sum(), losses

    grad_fn = jax.value_and_grad(total, has_aux=True)
    tree = jax.tree_util.tree_map
    b1 = jnp.asarray(advi.adam_b1, dtype)
    b2 = jnp.asarray(advi.adam_b2, dtype)

    def step(carry, i):
        params, m, v, _ = carry
        eps = jax.random.normal(
            jax.random.fold_in(key, i), (k_mc, b, p), dtype
        )
        (_, losses), g = grad_fn(params, eps)
        t = jnp.asarray(i + 1, dtype)
        m = tree(lambda a, gg: b1 * a + (1.0 - b1) * gg, m, g)
        v = tree(lambda a, gg: b2 * a + (1.0 - b2) * gg * gg, v, g)
        params = tree(
            lambda pp, mm, vv: pp
            - advi.learning_rate
            * (mm / (1.0 - b1**t))
            / (jnp.sqrt(vv / (1.0 - b2**t)) + advi.adam_eps),
            params, m, v,
        )
        return (params, m, v, losses), None

    zeros = (jnp.zeros_like(mu0), jnp.zeros_like(rho0))
    init = ((mu0, rho0), zeros, zeros, jnp.zeros((b,), dtype))
    (params, _, _, losses), _ = jax.lax.scan(
        step, init, jnp.arange(advi.num_steps)
    )
    return AdviPosterior(mu=params[0], rho=params[1], elbo=-losses)


_fit_advi_jit = jax.jit(_fit_advi, static_argnames=("config", "advi"))


def fit_advi(
    theta0: jnp.ndarray,
    data: FitData,
    key: jax.Array,
    config: ProphetConfig,
    advi: Optional[AdviConfig] = None,
) -> AdviPosterior:
    """Fit every series' mean-field posterior in one compiled program.

    Args:
      theta0: (B, P) warm start — the MAP fit's theta.
      data:   the SAME padded FitData the MAP solve ran on.
      key:    PRNG key; the whole loop is deterministic under it.
    """
    advi = AdviConfig() if advi is None else advi
    return _fit_advi_jit(theta0, data, key, config, advi)


def save_posterior(
    version_dir: str,
    post: AdviPosterior,
    *,
    seed: int,
    num_steps: int,
) -> str:
    """Persist the posterior into a registry version dir, atomically.

    One ``.npz`` with an identity header — readers reject a format or
    numerics mismatch instead of sampling from stale parameters.
    """
    path = os.path.join(version_dir, POSTERIOR_FILE)
    mu = np.asarray(post.mu, np.float32)
    rho = np.asarray(post.rho, np.float32)
    elbo = np.asarray(post.elbo, np.float32)
    header = json.dumps({
        "format": POSTERIOR_FORMAT,
        "numerics_rev": NUMERICS_REV,
        "n_series": int(mu.shape[0]),
        "num_params": int(mu.shape[1]),
        "seed": int(seed),
        "num_steps": int(num_steps),
    }).encode()

    def _write(f):
        buf = io.BytesIO()
        np.savez(buf, header=np.frombuffer(header, np.uint8),
                 mu=mu, rho=rho, elbo=elbo)
        f.write(buf.getvalue())

    atomic_write(path, _write)
    return path


def load_posterior(version_dir: str):
    """(AdviPosterior, header dict) or None when absent/unusable.

    An unreadable or mismatched artifact degrades to None — callers
    fall back to the MAP predictive tier, never to stale draws.
    """
    path = os.path.join(version_dir, POSTERIOR_FILE)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            header = json.loads(bytes(z["header"].tobytes()).decode())
            mu, rho, elbo = z["mu"], z["rho"], z["elbo"]
    except Exception:
        # A torn or half-written artifact degrades to the MAP tier —
        # same posture as a torn plane: never sample from suspect bytes.
        return None
    if header.get("format") != POSTERIOR_FORMAT:
        return None
    if header.get("numerics_rev") != NUMERICS_REV:
        return None
    if mu.shape != rho.shape or mu.shape[0] != elbo.shape[0]:
        return None
    return AdviPosterior(mu=mu, rho=rho, elbo=elbo), header
