"""Calibration eval gate: do the served intervals mean what they say?

An interval product can regress without a single bitwise diff — widen
the posterior, mis-scale the noise, drop a seasonality from the draw
path, and every test that pins bytes still passes while the "80%"
band covers 99% or 40% of reality.  The only gate that catches the
whole class is the definition itself: **empirical coverage vs
nominal** on held-out data.

``run_calibration_smoke`` is that gate in one process: fit the shared
demo dataset with the last ``holdout`` observations withheld, advance
the fleet to the ADVI tier, publish the quantile plane, and score the
plane's own served columns against the withheld truth per horizon
bucket.  The headline metric is

    coverage_abs_gap = max over buckets |empirical - nominal|

for the outer-quantile interval (with per-quantile gaps recorded
alongside), and the report joins RUNHISTORY as the ``calibration`` row
family under ``[tool.tsspark.slo.calibration]`` — a coverage drift
across commits trips the regression sentinel exactly like a latency
regression would.  The same run times the ADVI fit
(``advi_series_per_s``) and the plane's interval-read latency
(``qread_p99_ms``), and runs a small NUTS gold audit
(:mod:`~tsspark_tpu.uncertainty.gold`) conditioned on the SAME
truncated data, so one smoke exercises every rung of the ladder.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Sequence

import numpy as np

from tsspark_tpu.obs import context as obs

__all__ = [
    "DEFAULT_HOLDOUT",
    "coverage_eval",
    "evaluate_version",
    "run_calibration_smoke",
    "run_uncertainty_bench",
]

DEFAULT_HOLDOUT = 28


def coverage_eval(
    qcols: Dict[int, np.ndarray],
    y_true: np.ndarray,
    valid: Optional[np.ndarray] = None,
) -> Dict:
    """Score quantile columns against aligned truth.

    Args:
      qcols:  ``{permille: (n, H) float}`` served quantile values.
      y_true: ``(n, H)`` held-out observations, data units.
      valid:  optional ``(n, H)`` bool — positions to score (mask
        holes and unaligned grid points drop out of the average).

    Returns per-quantile empirical rates/gaps plus the outer-interval
    coverage — ``coverage_abs_gap`` is the interval's |empirical -
    nominal|.
    """
    y = np.asarray(y_true, np.float64)
    valid = (np.ones(y.shape, bool) if valid is None
             else np.asarray(valid, bool))
    n_valid = int(valid.sum())
    if n_valid == 0:
        raise ValueError("coverage_eval: no valid (series, step) cells")
    pms = sorted(qcols)
    per_q = {}
    for pm in pms:
        col = np.asarray(qcols[pm], np.float64)
        rate = float((y <= col)[valid].mean())
        per_q[pm] = {
            "nominal": pm / 1000.0,
            "empirical": round(rate, 6),
            "abs_gap": round(abs(rate - pm / 1000.0), 6),
        }
    lo, hi = pms[0], pms[-1]
    inside = ((y >= np.asarray(qcols[lo], np.float64))
              & (y <= np.asarray(qcols[hi], np.float64)))
    cov = float(inside[valid].mean())
    nominal = (hi - lo) / 1000.0
    return {
        "n_cells": n_valid,
        "interval": [lo, hi],
        "interval_nominal": round(nominal, 6),
        "interval_empirical": round(cov, 6),
        "coverage_abs_gap": round(abs(cov - nominal), 6),
        "quantile_gaps": per_q,
    }


def evaluate_version(
    registry,
    version: int,
    ds_future: np.ndarray,
    y_future: np.ndarray,
    *,
    mask_future: Optional[np.ndarray] = None,
) -> Optional[Dict]:
    """Score one version's PUBLISHED quantile plane against held-out
    truth, per horizon bucket.

    The eval reads the plane's own columns (``qplane.attach`` +
    ``quantile_batch``) — it gates the served artifact, not a parallel
    recomputation.  Grid cells are aligned to ``ds_future`` by value;
    cells whose grid point falls off the holdout (or lands between
    observations — irregular cadences) drop out.  Returns None when
    the version has no attached quantile plane.
    """
    from tsspark_tpu.uncertainty import qplane

    snap = registry.load(int(version))
    try:
        view = qplane.attach(registry.version_dir(int(version)),
                             expected_n=len(snap.series_ids))
    except qplane.QuantilePlaneError:
        return None
    ds_future = np.asarray(ds_future, np.float64)
    y_future = np.asarray(y_future, np.float64)
    n = len(snap.series_ids)
    idx = np.arange(n, dtype=np.int64)
    buckets = {}
    gaps = []
    for hb in view.buckets:
        grid, cols = qplane.quantile_batch(view, snap, idx, int(hb))
        # Value-align each series' grid to the holdout calendar; a
        # miss (beyond the holdout, or off-cadence) is just unscored.
        pos = np.clip(np.searchsorted(ds_future, grid), 0,
                      len(ds_future) - 1)
        matched = np.isclose(ds_future[pos], grid)
        y_t = y_future[np.arange(n)[:, None], pos]
        valid = matched
        if mask_future is not None:
            valid = valid & np.asarray(
                mask_future, bool)[np.arange(n)[:, None], pos]
        if not valid.any():
            continue
        rep = coverage_eval(cols, y_t, valid)
        buckets[str(int(hb))] = rep
        gaps.append(rep["coverage_abs_gap"])
    if not buckets:
        return None
    return {
        "mode": view.mode,
        "draws": view.draws,
        "seed": view.seed,
        "coverage_abs_gap": max(gaps),
        "buckets": buckets,
    }


def run_calibration_smoke(
    scratch: str,
    *,
    n_series: int = 24,
    seed: int = 0,
    holdout: int = DEFAULT_HOLDOUT,
    horizons: Sequence[int] = (7, 14, 28),
    data_root: Optional[str] = None,
    gold_audit: bool = True,
    read_probes: int = 200,
) -> Dict:
    """The end-to-end uncertainty smoke: fit-minus-holdout, ADVI
    advance, qplane publish, coverage eval, read-latency probe, gold
    audit.  Returns the ``kind="calibration-eval"`` report dict (the
    caller persists it and feeds the sentinel)."""
    import jax
    import jax.numpy as jnp

    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig, McmcConfig,
    )
    from tsspark_tpu.data import plane
    from tsspark_tpu.models.prophet.design import prepare_fit_data
    from tsspark_tpu.serve.__main__ import _report_identity
    from tsspark_tpu.serve.registry import ParamRegistry
    from tsspark_tpu.uncertainty import advi as advi_mod
    from tsspark_tpu.uncertainty import gold as gold_mod
    from tsspark_tpu.uncertainty import qplane

    t_start = time.perf_counter()
    config = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=3,
    )
    spec = plane.DatasetSpec(
        generator="demo_weekly", n_series=int(n_series),
        n_timesteps=180, seed=int(seed),
    )
    batch = plane.open_batch(plane.ensure(spec, root=data_root))
    ds = np.asarray(batch.ds, np.float64)
    y = np.asarray(batch.y)
    mask = None if batch.mask is None else np.asarray(batch.mask)
    cut = len(ds) - int(holdout)
    if cut < 8:
        raise ValueError(
            f"holdout {holdout} leaves only {cut} fit points")
    ds_fit, y_fit = ds[:cut], y[:, :cut]
    mask_fit = None if mask is None else mask[:, :cut]

    backend = get_backend("tpu", config, SolverConfig(max_iters=25))
    t0 = time.perf_counter()
    state = backend.fit(jnp.asarray(ds_fit), jnp.asarray(y_fit))
    fit_s = round(time.perf_counter() - t0, 3)

    registry = ParamRegistry(os.path.join(scratch, "registry"), config)
    v = registry.publish(state, np.asarray(batch.series_ids),
                         step=np.ones(int(n_series)))

    # ADVI advance over the SAME truncated design the MAP solve saw.
    data, _meta = prepare_fit_data(ds_fit, y_fit, config,
                                   mask=mask_fit)
    t0 = time.perf_counter()
    post = advi_mod.fit_advi(
        np.nan_to_num(np.asarray(state.theta, np.float32)), data,
        jax.random.PRNGKey(int(seed)), config,
    )
    advi_s = round(time.perf_counter() - t0, 3)
    advi_mod.save_posterior(registry.version_dir(int(v)), post,
                            seed=int(seed), num_steps=200)

    t0 = time.perf_counter()
    qpub = qplane.maybe_publish(registry, int(v), backend,
                                horizons=tuple(horizons))
    publish_s = round(time.perf_counter() - t0, 3)
    if qpub is None:
        raise RuntimeError("calibration smoke: qplane publish refused")

    ds_future, y_future = ds[cut:], y[:, cut:]
    mask_future = None if mask is None else mask[:, cut:]
    calib = evaluate_version(registry, int(v), ds_future, y_future,
                             mask_future=mask_future)
    if calib is None:
        raise RuntimeError("calibration smoke: no scorable plane")

    # Interval-read latency: small Zipf-ish random gathers, the hot
    # read shape.  Pure mmap path — this is qread_p99_ms.
    snap = registry.load(int(v))
    view = qplane.attach(registry.version_dir(int(v)),
                         expected_n=int(n_series))
    rng = np.random.default_rng(int(seed))
    hbs = list(view.buckets)
    walls = []
    for _ in range(int(read_probes)):
        k = int(rng.integers(1, min(9, n_series + 1)))
        idx = rng.choice(n_series, size=k, replace=False)
        hb = int(hbs[int(rng.integers(len(hbs)))])
        t1 = time.perf_counter()
        qplane.quantile_batch(view, snap, np.sort(idx), hb)
        walls.append((time.perf_counter() - t1) * 1e3)
    qread = {k: round(float(np.percentile(walls, q)), 3)
             for k, q in (("p50", 50), ("p95", 95), ("p99", 99))}

    gold_rep = None
    if gold_audit:
        gold_rep = gold_mod.audit_version(
            registry, version=int(v),
            arrays=(ds_fit, y_fit, mask_fit, None),
            max_series=2, seed=int(seed),
            mcmc_config=McmcConfig(num_samples=60, num_warmup=60,
                                   num_leapfrog=8),
        )

    report = {
        **_report_identity(registry),
        "kind": "calibration-eval",
        "n_series": int(n_series),
        "holdout": int(holdout),
        "seed": int(seed),
        "wall_s": round(time.perf_counter() - t_start, 3),
        "calibration": {
            "mode": calib["mode"],
            "coverage_abs_gap": calib["coverage_abs_gap"],
            "buckets": calib["buckets"],
            "draws": calib["draws"],
            "fit_s": fit_s,
            "advi_fit_s": advi_s,
            "advi_series_per_s": (round(n_series / advi_s, 1)
                                  if advi_s > 0 else None),
            "publish_s": publish_s,
            "nbytes": qpub.get("nbytes"),
            "qread_ms": qread,
            "qread_p99_ms": qread["p99"],
            "gold": None if gold_rep is None else {
                "qdiv_max": gold_rep["qdiv_max"],
                "qdiv_mean": gold_rep["qdiv_mean"],
                "rhat_max": gold_rep["rhat_max"],
                "ess_min": gold_rep["ess_min"],
                "hmc_divergences": gold_rep["hmc_divergences"],
                "rows": gold_rep["rows"],
            },
        },
    }
    obs.event("calibration.smoke",
              coverage_abs_gap=calib["coverage_abs_gap"],
              mode=calib["mode"], qread_p99_ms=qread["p99"])
    return report


def run_uncertainty_bench(args) -> int:
    """The ``bench --uncertainty`` runner (argparse namespace from
    bench.py: series/seed/dir/report/data_root).  Persists the
    ``kind="calibration-eval"`` report as ``BENCH_uncertainty_*``,
    joins it to RUNHISTORY as the ``calibration`` row family, and
    gates under ``[tool.tsspark.slo.calibration]``."""
    import json

    from tsspark_tpu.io import atomic_write
    from tsspark_tpu.serve.__main__ import _sentinel_gate

    scratch = os.path.join(args.dir or ".", "uncertainty_scratch")
    obs.start_run(os.path.join(scratch, "spans.jsonl"))
    report = run_calibration_smoke(
        scratch, n_series=int(args.series), seed=int(args.seed),
        data_root=args.data_root,
    )
    out = args.report or f"BENCH_uncertainty_{int(time.time())}.json"
    atomic_write(out, lambda fh: json.dump(report, fh, indent=1),
                 mode="w")
    cal = report["calibration"]
    gold = cal.get("gold") or {}
    print(
        f"uncertainty: mode {cal['mode']} | coverage gap "
        f"{cal['coverage_abs_gap']} (nominal-vs-empirical, worst "
        f"bucket) | advi {cal['advi_series_per_s']} series/s "
        f"({cal['advi_fit_s']}s) | qplane publish {cal['publish_s']}s "
        f"({cal['nbytes']} B) | qread p50={cal['qread_ms']['p50']} "
        f"p99={cal['qread_p99_ms']} ms | gold qdiv_max "
        f"{gold.get('qdiv_max')} rhat_max {gold.get('rhat_max')} | "
        f"report -> {out}"
    )
    return _sentinel_gate(report, out)
