"""Uncertainty tier: intervals as a served product, not a side path.

The ladder (cheap to gold), after "Going NUTS with ADVI" (PAPERS.md,
arXiv 2601.20120) measured ADVI intervals at NUTS quality for this
model family at a fraction of the cost:

* **MAP predictive** — the existing ``models/prophet/predict.py``
  recipe: simulated future changepoints + observation noise around the
  MAP point.  Free (no extra fit), but ignores parameter uncertainty.
* **ADVI** (:mod:`~tsspark_tpu.uncertainty.advi`) — a mean-field
  Gaussian posterior per series, fitted by a vmapped ELBO loop over the
  same padded design tensors as the L-BFGS MAP solve.  The default
  served tier.
* **NUTS gold** (:mod:`~tsspark_tpu.uncertainty.gold`) — full HMC
  chains (``ops/hmc.py``) on a deterministic sampled subset per
  version, auditing the ADVI intervals.

Served through the **quantile plane**
(:mod:`~tsspark_tpu.uncertainty.qplane`): quantile forecast columns
published next to the point-forecast plane with the same spec-first /
CRC-sentinel protocol, answered from an mmap gather with zero JAX
dispatch, and regression-gated by the **calibration eval**
(:mod:`~tsspark_tpu.uncertainty.calibrate`) — empirical coverage vs
nominal per horizon bucket under ``[tool.tsspark.slo.calibration]``.
"""

from tsspark_tpu.uncertainty.advi import (  # noqa: F401
    AdviPosterior,
    fit_advi,
    load_posterior,
    save_posterior,
)
from tsspark_tpu.uncertainty import calibrate  # noqa: F401
from tsspark_tpu.uncertainty import gold  # noqa: F401
from tsspark_tpu.uncertainty import qplane  # noqa: F401

__all__ = [
    "AdviPosterior",
    "fit_advi",
    "load_posterior",
    "save_posterior",
    "calibrate",
    "gold",
    "qplane",
]
