"""``bench --scale``: the million-series ladder over ONE data plane.

ROADMAP item 2 ("prove millions of users") needs more than a big fit:
ingest, fit, publish, and serve must all survive the same series count
against the same storage, and every rung must leave a comparable row in
the cross-run history.  This module drives that ladder:

    ingest  — the shared columnar data plane (``data/plane``),
              block-seeded so a 1M-series dataset is representable
              without ever materializing it whole;
    fit     — the mesh-resident single-program path
              (``tsspark_tpu.resident``; meshless boxes degrade to the
              chunk-file protocol with the same artifacts);
    publish — ``orchestrate.publish_fit_state`` into a serve registry
              whose snapshots land as the memmap column plane
              (``serve.snapplane``) plus the archival npz;
    serve   — the replica pool (or, on the smoke rung, one in-process
              engine) over that registry: time-to-first-request, a
              Zipf request mix, one mid-run version flip through the
              ahead-of-time materializer, and sharing-aware RSS
              accounting (``utils.procmem``) proving N replicas map ONE
              physical snapshot copy.

Rungs: ``smoke`` (tier-1 sized, in-process serve — the rung the test
suite and the regression sentinel accrue baselines from) then
``30k -> 100k -> 1m``.  Each rung emits one ``SCALE_<rung>_<unix>.json``
report; the history index keys its workload ``scale_<rung>`` so a 1M
row can never baseline against a smoke row, and the sentinel judges
``rss_mb_per_replica`` / ``agg_requests_per_s`` /
``time_to_first_request_s`` / ``flip_p99_ms`` against
``[tool.tsspark.slo.scale]``.

The RSS-reduction claim is MEASURED, not asserted: after the mmap pool
is scored, the same rung optionally restarts the pool with
``TSSPARK_SNAPSHOT_FORMAT=npz`` (each replica materializing a private
heap copy, the pre-plane behavior) and the report stamps both pools'
``RssAnon``/``Pss`` plus ``rss_reduction_x`` — private npz heap bytes
across the pool over the plane's shared resident bytes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

#: Serving horizons every rung exercises (two pow-2 buckets).
HORIZONS = (7, 14)


@dataclasses.dataclass(frozen=True)
class ScaleRung:
    """One rung of the ladder (sizes chosen so the top rung completes
    end-to-end on a one-core box; the ladder is about what breaks at
    scale, not about repeating the M5 depth benchmark)."""

    name: str
    series: int
    timesteps: int
    max_iters: int
    chunk: int
    pool_replicas: int      # 0 = in-process engine serve (tier-1)
    requests: int           # serve requests (split around the flip)
    hot: int                # hot-set size the flip materializes
    sample: int             # distinct ids in the request mix
    rss_compare: bool       # also run the npz private-heap pool


RUNGS: Dict[str, ScaleRung] = {
    "smoke": ScaleRung("smoke", 1024, 64, 8, 512, 0, 96, 24, 256,
                       False),
    "30k": ScaleRung("30k", 30_490, 128, 12, 2048, 4, 320, 64, 2048,
                     True),
    "100k": ScaleRung("100k", 100_000, 96, 8, 4096, 4, 320, 64, 2048,
                      True),
    "1m": ScaleRung("1m", 1_000_000, 64, 6, 8192, 4, 320, 64, 2048,
                    True),
}

#: The default ladder ``--scale ladder`` climbs, in order.
LADDER: Sequence[str] = ("30k", "100k", "1m")


def _config():
    """The ladder's model config — deliberately the serve loadgen's
    demo config, so compile caches and registry fingerprints are shared
    with the serving tests."""
    from tsspark_tpu.config import ProphetConfig, SeasonalityConfig

    return ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=3,
    )


def _identity() -> Dict:
    import jax

    from tsspark_tpu.config import NUMERICS_REV
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.history import git_rev
    from tsspark_tpu.utils import checkpoint as ckpt

    return {
        "kind": "scale-ladder",
        "unix": round(time.time(), 3),
        "trace_id": obs.trace_id(),
        "numerics_rev": NUMERICS_REV,
        "git_rev": git_rev(),
        "device": str(jax.devices()[0]),
        "config_fingerprint": ckpt.config_fingerprint(_config()),
    }


def _pct(vals: List[float], q: float) -> Optional[float]:
    return (round(float(np.percentile(np.asarray(vals), q)) * 1e3, 3)
            if vals else None)


def _mean(vals) -> Optional[float]:
    vals = [v for v in vals if v is not None]
    return round(float(np.mean(vals)), 3) if vals else None


def _write_scale_report(report: Dict,
                        path: Optional[str] = None) -> str:
    """Persist one rung's report as ``SCALE_<rung>_<unix>.json``
    (atomic, like every other report artifact)."""
    from tsspark_tpu.utils.atomic import atomic_write

    out = path or (f"SCALE_{report.get('rung')}"
                   f"_{int(report.get('unix', time.time()))}.json")
    atomic_write(out, lambda fh: json.dump(report, fh, indent=1),
                 mode="w")
    return out


# ---------------------------------------------------------------------------
# serve-side measurement
# ---------------------------------------------------------------------------


def _request_mix(rung: ScaleRung, ids: np.ndarray, seed: int = 0):
    """Deterministic Zipf-ish mix over a row sample spread across the
    WHOLE id range (random rows = random pages — the on-demand paging
    the mmap snapshot must serve).  Returns (sample_ids, picks) where
    picks is a list of (series_list, horizon)."""
    rng = np.random.default_rng(seed)
    n = len(ids)
    sample_rows = np.sort(rng.choice(n, size=min(rung.sample, n),
                                     replace=False))
    sample = ids[sample_rows]
    w = 1.0 / (1.0 + np.arange(len(sample)))
    w /= w.sum()
    picks = []
    for i in range(rung.requests):
        k = int(rng.integers(1, min(9, len(sample) + 1)))
        rows = rng.choice(len(sample), size=k, replace=False, p=w)
        picks.append(([str(sample[j]) for j in rows],
                      int(HORIZONS[i % len(HORIZONS)])))
    return sample, picks


def _pool_mem(stats: Dict) -> Dict:
    """Fold ``ReplicaPool.stats()`` per-replica memory into the rung's
    RSS metrics (sharing-aware: see utils.procmem)."""
    per = [v.get("mem") or {} for v in stats["replicas"].values()
           if isinstance(v, dict) and not v.get("down")]
    snap_pss = [((m.get("snap") or {}).get("pss_mb")) for m in per]
    return {
        "replicas_sampled": len(per),
        "rss_mb_per_replica": _mean([m.get("rss_mb") for m in per]),
        "pss_mb_per_replica": _mean([m.get("pss_mb") for m in per]),
        "rss_anon_mb_per_replica": _mean(
            [m.get("rss_anon_mb") for m in per]
        ),
        "snap_pss_total_mb": (round(sum(v for v in snap_pss
                                        if v is not None), 3)
                              if any(v is not None for v in snap_pss)
                              else None),
        "per_replica": per,
    }


def _serve_pool(rung: ScaleRung, registry, ids: np.ndarray,
                scratch: str, v_next: int) -> Dict:
    """Pool-serve one rung: spawn, first-request, mix, mid-run flip,
    sharing-aware memory."""
    from tsspark_tpu.serve.pool import ReplicaPool

    sample, picks = _request_mix(rung, ids)
    hot = [str(s) for s in sample[:rung.hot]]
    pool = ReplicaPool(
        os.path.join(scratch, "pool"), registry.root,
        n_replicas=rung.pool_replicas,
    )
    t_start = time.monotonic()
    pool.start()
    first = pool.forecast([str(sample[0])], HORIZONS[0])
    t_first = time.monotonic() - t_start
    assert first.get("ok"), f"first request failed: {first}"
    # Warm the hot set ahead of the measured window (the steady state a
    # production pool serves; the flip re-warms the same set for v2).
    for slot in range(rung.pool_replicas):
        try:
            pool._request_slot(slot, {
                "cmd": "warm", "version": registry.active_version(),
                "series_ids": hot, "horizons": list(HORIZONS),
            }, timeout_s=600.0)
        except Exception:
            pass
    latencies: List[float] = []
    done_at: List[float] = []
    outcomes = {"ok": 0, "failed": 0}
    flip = {}
    t0 = time.monotonic()
    for i, (sids, h) in enumerate(picks):
        if i == len(picks) // 2:
            t_f0 = time.monotonic()
            pool.activate(v_next, hot_series=hot,
                          horizons=HORIZONS)
            flip = {"version": v_next, "t0": t_f0,
                    "t1": time.monotonic()}
        t_r0 = time.monotonic()
        try:
            resp = pool.forecast(sids, h)
            ok = bool(resp.get("ok"))
        except Exception:
            ok = False
        t_r1 = time.monotonic()
        outcomes["ok" if ok else "failed"] += 1
        if ok:
            latencies.append(t_r1 - t_r0)
            done_at.append(t_r1)
    wall = time.monotonic() - t0
    stats = pool.stats()
    mem = _pool_mem(stats)
    win = [lat for lat, done in zip(latencies, done_at)
           if flip and flip["t0"] <= done <= flip["t1"] + 1.0]
    out = {
        "mode": "pool",
        "replicas": rung.pool_replicas,
        "time_to_first_request_s": round(t_first, 3),
        "wall_s": round(wall, 3),
        "requests": rung.requests,
        "outcomes": outcomes,
        "agg_requests_per_s": (round(rung.requests / wall, 2)
                               if wall > 0 else None),
        "latency_ms": {"p50": _pct(latencies, 50),
                       "p99": _pct(latencies, 99)},
        "flip": {
            "version": flip.get("version"),
            "wall_s": (round(flip["t1"] - flip["t0"], 3)
                       if flip else None),
            "n_in_window": len(win),
            "p99_ms": _pct(win, 99),
        },
        "failovers": stats["failovers"],
        "wrong_version": stats["wrong_version"],
        "mem": mem,
    }
    pool.stop()
    return out


def _serve_engine(rung: ScaleRung, registry, ids: np.ndarray,
                  v_next: int) -> Dict:
    """In-process engine serve (the smoke rung / tier-1 path): same
    stages, no replica processes — memory read from /proc/self."""
    from tsspark_tpu.serve.engine import PredictionEngine
    from tsspark_tpu.utils.procmem import mapped_file_mem, proc_mem

    sample, picks = _request_mix(rung, ids)
    hot = [str(s) for s in sample[:rung.hot]]
    t_start = time.monotonic()
    engine = PredictionEngine(registry)
    engine.forecast([str(sample[0])], HORIZONS[0])
    t_first = time.monotonic() - t_start
    engine.materialize(hot, HORIZONS)
    latencies: List[float] = []
    done_at: List[float] = []
    failed = 0
    flip = {}
    t0 = time.monotonic()
    for i, (sids, h) in enumerate(picks):
        if i == len(picks) // 2:
            t_f0 = time.monotonic()
            # The engine analog of the pool's materialize->flip: pages
            # warm during prefetch (the plane's CRC sweep), forecasts
            # for the hot set land in the cache's warm window, then the
            # pointer flips.
            engine.prefetch(v_next)
            engine.materialize(hot, HORIZONS, version=v_next)
            registry.activate(v_next)
            flip = {"version": v_next, "t0": t_f0,
                    "t1": time.monotonic()}
        t_r0 = time.monotonic()
        try:
            engine.forecast(sids, h)
        except Exception:
            failed += 1  # a shed/failed request must not abort the rung
            continue
        t_r1 = time.monotonic()
        latencies.append(t_r1 - t_r0)
        done_at.append(t_r1)
    wall = time.monotonic() - t0
    win = [lat for lat, done in zip(latencies, done_at)
           if flip and flip["t0"] <= done <= flip["t1"] + 1.0]
    mem = proc_mem()
    return {
        "mode": "engine",
        "replicas": 0,
        "time_to_first_request_s": round(t_first, 3),
        "wall_s": round(wall, 3),
        "requests": rung.requests,
        "outcomes": {"ok": len(latencies), "failed": failed},
        "agg_requests_per_s": (round(rung.requests / wall, 2)
                               if wall > 0 else None),
        "latency_ms": {"p50": _pct(latencies, 50),
                       "p99": _pct(latencies, 99)},
        "flip": {
            "version": flip.get("version"),
            "wall_s": (round(flip["t1"] - flip["t0"], 3)
                       if flip else None),
            "n_in_window": len(win),
            "p99_ms": _pct(win, 99),
        },
        "mem": {
            "replicas_sampled": 1,
            "rss_mb_per_replica": mem.get("rss_mb"),
            "pss_mb_per_replica": mem.get("pss_mb"),
            "rss_anon_mb_per_replica": mem.get("rss_anon_mb"),
            "snap_pss_total_mb": mapped_file_mem().get("pss_mb"),
        },
        "cache": engine.cache.stats(),
    }


def _rss_comparison(rung: ScaleRung, registry, ids: np.ndarray,
                    scratch: str, mmap_mem: Dict) -> Dict:
    """Restart the pool with snapshots pinned to the npz format (each
    replica parses a PRIVATE heap copy — the pre-plane behavior) and
    measure the same sharing-aware counters.  The reduction ratio is
    private npz snapshot bytes across the pool over the plane's shared
    resident bytes."""
    from tsspark_tpu.serve.pool import ReplicaPool

    sample, _ = _request_mix(rung, ids)
    hot = [str(s) for s in sample[:rung.hot]]
    prev = os.environ.get("TSSPARK_SNAPSHOT_FORMAT")
    os.environ["TSSPARK_SNAPSHOT_FORMAT"] = "npz"
    try:
        pool = ReplicaPool(
            os.path.join(scratch, "pool_npz"), registry.root,
            n_replicas=rung.pool_replicas,
        )
        pool.start()
        pool.forecast([str(sample[0])], HORIZONS[0])
        for slot in range(rung.pool_replicas):
            try:
                pool._request_slot(slot, {
                    "cmd": "warm",
                    "version": registry.active_version(),
                    "series_ids": hot, "horizons": list(HORIZONS),
                }, timeout_s=600.0)
            except Exception:
                pass
        npz_mem = _pool_mem(pool.stats())
        pool.stop()
    finally:
        if prev is None:
            os.environ.pop("TSSPARK_SNAPSHOT_FORMAT", None)
        else:
            os.environ["TSSPARK_SNAPSHOT_FORMAT"] = prev
    out = {"npz": npz_mem}
    anon_npz = npz_mem.get("rss_anon_mb_per_replica")
    anon_mmap = mmap_mem.get("rss_anon_mb_per_replica")
    shared = mmap_mem.get("snap_pss_total_mb")
    if None not in (anon_npz, anon_mmap) and shared:
        # Numerator: the private anonymous bytes the npz snapshots cost
        # across the pool (npz replicas' anon heap minus the mmap
        # replicas' anon baseline — same engine, same warm set).
        # Denominator: the ONE physical copy the plane keeps resident.
        private = max(0.0, anon_npz - anon_mmap) * rung.pool_replicas
        out["snapshot_private_mb_total"] = round(private, 3)
        out["snapshot_shared_mb_total"] = shared
        out["rss_reduction_x"] = round(private / shared, 2)
    return out


# ---------------------------------------------------------------------------
# one rung, end to end
# ---------------------------------------------------------------------------


def run_rung(rung, *, data_root: Optional[str] = None,
             scratch_root: Optional[str] = None,
             report_path: Optional[str] = None,
             deadline_s: Optional[float] = None,
             sentinel: Optional[bool] = None,
             rss_compare: Optional[bool] = None) -> Dict:
    """Drive one rung ingest -> fit -> publish -> serve; returns the
    report dict (also written as ``SCALE_*.json`` and, unless the
    sentinel is opted out, judged against the rolling baseline)."""
    import tempfile

    from tsspark_tpu import orchestrate, resident
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import plane
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.serve import snapplane
    from tsspark_tpu.serve.registry import ParamRegistry

    if isinstance(rung, str):
        rung = RUNGS[rung]
    cfg = _config()
    scratch = os.path.join(
        scratch_root or tempfile.gettempdir(),
        f"tsscale_{rung.name}_{rung.series}x{rung.timesteps}"
        f"_{plane.dataset_fingerprint()}",
    )
    os.makedirs(scratch, exist_ok=True)
    prev_run = obs.start_run(os.path.join(scratch, "spans.jsonl"))
    t_rung0 = time.time()
    report = {**_identity(), "rung": rung.name,
              "series": rung.series, "timesteps": rung.timesteps}
    try:
        # ---- ingest: the shared columnar plane ----------------------
        spec = plane.DatasetSpec(
            generator="demo_weekly", n_series=rung.series,
            n_timesteps=rung.timesteps, seed=2,
        )
        dset_dir = plane.dataset_dir(spec, data_root)
        warm = plane.is_complete(dset_dir)
        t0 = time.time()
        dset_dir = plane.ensure(spec, root=data_root)
        ingest_s = time.time() - t0
        ids = plane.series_ids(spec)
        report["ingest"] = {"warm": warm,
                            "ingest_s": round(ingest_s, 3),
                            "dataset": os.path.basename(dset_dir)}

        # ---- fit: the mesh-resident path ----------------------------
        out_dir = os.path.join(scratch, "out")
        os.makedirs(out_dir, exist_ok=True)
        solver = SolverConfig(max_iters=rung.max_iters)
        orchestrate.save_run_config(out_dir, cfg, solver)
        t0 = time.time()
        fit_state = resident.run_resident(
            data_dir=dset_dir, out_dir=out_dir, series=rung.series,
            chunk=rung.chunk, phase1_iters=0, no_phase1_tune=True,
            deadline=(time.time() + deadline_s
                      if deadline_s else None),
        )
        fit_s = time.time() - t0
        n_done = sum(hi - lo for lo, hi in
                     orchestrate.completed_ranges(out_dir))
        report["fit"] = {
            "fit_s": round(fit_s, 3),
            "fit_path": fit_state.get("fit_path"),
            "complete": bool(fit_state.get("complete")),
            "series_done": n_done,
            "series_per_s": (round(n_done / fit_s, 2)
                             if fit_s > 0 else None),
        }
        if not fit_state.get("complete"):
            report["complete"] = False
            return report

        # ---- publish: mmap plane + archival npz ---------------------
        registry = ParamRegistry(
            os.path.join(scratch, "registry"), cfg,
        )
        t0 = time.time()
        v1 = orchestrate.publish_fit_state(registry, out_dir, ids)
        publish_s = time.time() - t0
        vdir = os.path.join(registry.root, f"v{v1:06d}")
        nbytes = snapplane.snapshot_nbytes(vdir)
        report["publish"] = {
            "publish_s": round(publish_s, 3),
            "version": v1,
            "snapshot_mb": (round(nbytes / 1e6, 3)
                            if nbytes else None),
            "format": registry.snapshot_format,
        }
        # The mid-run flip target, published before the clock starts.
        snap = registry.load(v1, fallback=False)
        v2 = registry.publish(
            snap.state._replace(
                theta=np.asarray(snap.state.theta) * 1.01
            ),
            ids, step=np.asarray(snap.step), activate=False,
        )

        # ---- serve: pool (or in-process engine) ---------------------
        if rung.pool_replicas:
            serve = _serve_pool(rung, registry, ids, scratch, v2)
            compare = (rung.rss_compare if rss_compare is None
                       else rss_compare)
            if compare:
                serve["rss_compare"] = _rss_comparison(
                    rung, registry, ids, scratch, serve["mem"]
                )
        else:
            serve = _serve_engine(rung, registry, ids, v2)
        report["serve"] = serve
        report["complete"] = True
        return report
    finally:
        report["wall_s"] = round(time.time() - t_rung0, 3)
        obs.end_run(prev_run)
        out = _write_scale_report(report, report_path)
        report["path"] = out
        if sentinel is None:
            sentinel = os.environ.get("TSSPARK_SENTINEL", "1") != "0"
        if sentinel:
            try:
                from tsspark_tpu.obs import regress

                verdict = regress.sentinel_report(
                    report, source=f"scale:{rung.name}"
                )
                if verdict is not None:
                    print(f"[scale] {regress.summarize(verdict)}")
                    report["sentinel_ok"] = verdict["ok"]
            except Exception as e:  # never mask the report itself
                print(f"[scale] sentinel skipped: {e!r}")


def run_ladder(rungs: Sequence[str] = LADDER, **kwargs) -> List[Dict]:
    """Climb the ladder rung by rung (each rung is independently
    resumable through the resident fit's chunk protocol)."""
    out = []
    for name in rungs:
        print(f"[scale] rung {name}: "
              f"{RUNGS[name].series} series x "
              f"{RUNGS[name].timesteps} steps")
        rep = run_rung(name, **kwargs)
        serve = rep.get("serve") or {}
        print(json.dumps({
            "rung": rep.get("rung"),
            "complete": rep.get("complete"),
            "fit_s": (rep.get("fit") or {}).get("fit_s"),
            "publish_s": (rep.get("publish") or {}).get("publish_s"),
            "ttfr_s": serve.get("time_to_first_request_s"),
            "agg_rps": serve.get("agg_requests_per_s"),
            "flip_p99_ms": (serve.get("flip") or {}).get("p99_ms"),
            "rss_reduction_x": (serve.get("rss_compare") or {}
                                ).get("rss_reduction_x"),
            "report": rep.get("path"),
        }), flush=True)
        out.append(rep)
        if not rep.get("complete"):
            break  # a failed rung gates the rungs above it
    return out
