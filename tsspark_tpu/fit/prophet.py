"""``tsspark_tpu.fit.prophet`` — migration alias for the reference's
``tsspark.fit.prophet`` module (BASELINE.json:5: "the piecewise-linear-trend
+ Fourier-seasonality design-matrix build and the L-BFGS MAP inner loop in
``tsspark.fit.prophet``").  A reference user's imports keep working with the
package rename; the canonical homes are ``tsspark_tpu.models.prophet.*`` and
``tsspark_tpu.config``."""

from tsspark_tpu.config import (  # noqa: F401
    DAILY,
    McmcConfig,
    ProphetConfig,
    RegressorConfig,
    SeasonalityConfig,
    SolverConfig,
    WEEKLY,
    YEARLY,
)
from tsspark_tpu.models.prophet.design import (  # noqa: F401
    FitData,
    ScalingMeta,
    prepare_fit_data,
    quantile_changepoints,
)
from tsspark_tpu.models.prophet.init import (  # noqa: F401
    curvature_diag,
    initial_theta,
    ridge_init,
)
from tsspark_tpu.models.prophet.loss import (  # noqa: F401
    neg_log_posterior,
    value_and_grad_batch,
    value_batch,
)
from tsspark_tpu.models.prophet.model import (  # noqa: F401
    FitState,
    McmcState,
    ProphetModel,
    fit_core,
)
from tsspark_tpu.models.prophet.predict import (  # noqa: F401
    forecast,
    prepare_predict_data,
)
from tsspark_tpu.models.prophet.seasonality import (  # noqa: F401
    auto_seasonalities,
    fourier_features,
    seasonal_feature_matrix,
)
