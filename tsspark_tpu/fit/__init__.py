"""Compatibility namespace mirroring the reference's ``tsspark.fit`` package
path (the driver north star names ``tsspark.fit.prophet`` as the module a
reference user knows; BASELINE.json:5).  Everything here is an alias onto
the canonical modules under ``tsspark_tpu.models.prophet``."""

from tsspark_tpu.fit import prophet  # noqa: F401
