"""Seeded fault-storm composition.

A storm is a deterministic function of ``(seed, profile)``: which fault
classes fire, at which injection points, with which windows and
parameters.  The schedule is data (``Injection`` rows recorded verbatim
in the ``CHAOS_*`` scorecard), so the same seed reproduces the same
storm on any machine — the property that turns "survived a fault storm"
from an anecdote into a regression gate.

Two kinds of injection:

* env-plan injections ride the resilience fault harness
  (``resilience.faults.FaultPlan`` via ``TSSPARK_FAULTS``), so the
  orchestrator's CHILD processes see the same storm the harness armed;
* direct injections (serve-queue overload bursts, mid-loadgen
  activation races) are performed by the harness itself at
  deterministic request indices.

Fault classes (docs/RESILIENCE.md "Chaos harness & failure domains"):

  worker-kill      fit worker dies (os._exit) right after landing a chunk
  torn-artifact    a just-saved chunk file is silently byte-flipped
  spawn-fail       a worker spawn fails before the child starts
  slow-io          a chunk fit stalls (sleep) — latency, not failure
  wedged-client    the accelerator probe reports a wedge (full profile)
  registry-corrupt the ACTIVE registry snapshot npz is byte-flipped
  snapshot-torn-shard a CRC-covered shard of the ACTIVE version's mmap
                   snapshot plane (serve/snapplane.py) is byte-flipped
                   mid-flip: the attach-time sentinel must reject the
                   plane and the fallback chain serve the last GOOD
                   version — never torn parameters
  stream-fault     streaming source polls raise transiently
  serve-fault      engine predict dispatches raise until the breaker opens
  queue-overload   a request burst exceeds the engine's bounded queue
  activation-race  a publish+activate lands mid-loadgen, racing the cache

Pool-scale classes (the serve replica pool, profiles with
``pool_replicas`` > 0):

  replica-kill           SIGKILL one replica mid-loadgen: in-flight and
                         queued requests fail over to the sibling shard
                         owner; the slot respawns under backoff
  front-crash            the pool front dies; a successor re-attaches to
                         the live replicas without restarting them
  split-brain-activation a replica stalls (SIGSTOP), its slot lease is
                         stolen by a replacement, a version activates,
                         then the zombie revives — lease fencing must
                         refuse it service, never a stale version

Data-plane classes (PR 9's columnar cache, profiles with
``plane_series`` > 0):

  plane-torn-shard   a landed shard's memmap rows are byte-flipped under
                     its sentinel: verify_shard must reject, repair must
                     re-land bitwise
  ingest-driver-kill the background ingest driver is SIGKILLed mid-fill:
                     the consumer self-produces the missing shards
                     (deterministic block seeding) and completes

Mesh-resident class (the single-program fit path, profiles with
``resident_series`` > 0):

  resident-kill      the resident sharded program's process dies (exit
                     fault at the ``resident_flush`` point) mid
                     flush-stream: a successor run must resume from the
                     last LANDED checkpoint flush, finish with
                     exactly-once coverage, and assemble a state
                     bitwise equal to a fault-free reference

Delta-refit class (tsspark_tpu.refit, profiles with ``refit_series``
> 0):

  refit-kill         the delta-refit child dies (exit fault at the
                     ``delta_publish`` point) MID DELTA-PUBLISH — after
                     its warm waves landed, while the new version's
                     copy-forward columns are half-written: the pool
                     must keep serving only the last complete version
                     (zero wrong-version), a successor must resume from
                     the landed chunk flushes (zero refit dispatches)
                     and re-publish, and the final snapshot's UNCHANGED
                     rows must be bitwise the prior active version's

Loop-storm class (the always-on scheduler, ``tsspark_tpu.sched``;
profiles with ``sched_storm``):

  loop-storm         a CHAIN of scheduler deaths, one per stage the
                     loop drives: exit faults at ``sched_detect``
                     (detect pinned, nothing fit), ``resident_flush``
                     (mid warm wave), ``delta_publish`` (copy-forward
                     half-written), ``sched_flip`` (published, not yet
                     flipped) — each successor scheduler must resume
                     the SAME pinned ``refit_plan.json`` — plus one
                     raw SIGKILL of the scheduler process mid-cycle.
                     Invariants: the pool serves only complete
                     versions throughout (zero wrong-version), the
                     final snapshot's unchanged rows are bitwise its
                     base's, and data-to-forecast freshness recovers
                     within the recovery budget after the storm.

Storage fault-domain classes (the durable-I/O layer ``tsspark_tpu.io``;
profiles with ``storage_storm``, docs/RESILIENCE.md "Storage fault
domain"):

  enospc-mid-publish  an injected ENOSPC (``io_write``, path-scoped to
                      the snapshot columns) kills a registry publish
                      mid-plane: the manifest never flips, the active
                      version keeps serving, and a retry publishes a
                      version bitwise equal to the fault-free one
  eio-on-flip         the manifest rename that activates a version
                      raises EIO: the flip must fail CLEAN (old pointer
                      intact, typed ``DiskIOError``) and succeed on
                      retry
  short-write-torn-column  a column payload is silently truncated
                      (unchecked ``write(2)`` return) and the publish
                      REPORTS SUCCESS: only the CRC sentinel can catch
                      it at attach — the fallback chain serves the last
                      good version, never torn parameters
  lost-fsync-then-kill  an activation's manifest rename lands only in
                      the page cache; the process is killed and the
                      rename rolled back (the crash lost it): the
                      survivor must observe the PRE-flip truth and a
                      successor re-activate cleanly
  disk-pressure-brownout  a byte budget strangles the storage root:
                      the degradation ladder must descend in order
                      (shed speculation -> reap -> pause ingest with
                      ``BackpressureError`` -> stale-flagged serving),
                      version-producing writes must be refused by the
                      budget gate while the active version KEEPS
                      serving, and relief must resume ingestion

Alert-stream fault-domain classes (the exactly-once anomaly alert
pipeline ``tsspark_tpu.alerts``; profiles with ``alerts_storm``):

  alert-scorer-kill   the scorer child (``python -m tsspark_tpu.alerts
                      --poll-once``) dies at the ``alert_publish``
                      point — between the record write and its CRC
                      sentinel, then again at ``alert_deliver`` mid
                      sink emit: the successor must re-score the
                      uncertified delta BITWISE and redeliver past the
                      watermark with zero duplicate keys
  alert-sink-brownout the delivery sink raises for a window: the
                      breaker opens, the watermark HOLDS (never
                      advances past an unacked record), and recovery
                      drains everything exactly once
  torn-alert-record   a certified alert record's bytes are flipped
                      under its sentinel: the CRC check must reject
                      it, the re-score converge bitwise, and delivery
                      dedup suppress every duplicate
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from tsspark_tpu.resilience.faults import FaultPlan

#: Injection point used for the registry-snapshot corruption (the
#: harness calls ``faults.corrupt_file`` on the active version's npz —
#: same exempt, deterministic corruption machinery as chunk files).
REGISTRY_SNAPSHOT_POINT = "registry_snapshot"


@dataclasses.dataclass(frozen=True)
class Injection:
    """One scheduled fault.  ``point`` is a resilience.faults injection
    point for env-plan rows, or a symbolic name for direct ones."""

    cls: str                  # fault class (scorecard key)
    stage: str                # orchestrate | registry | streaming | serve
    point: str
    mode: str                 # faults mode, or "direct"
    after: int = 0
    attempts: int = 1
    series: Optional[int] = None
    rc: int = 23
    delay_s: float = 0.0
    at_request: Optional[int] = None   # direct serve injections

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


@dataclasses.dataclass(frozen=True)
class StormProfile:
    """Workload + storm sizing for one harness run.

    ``run_orchestrate=False`` replaces the chunked-orchestrate stage
    (and its fault-free reference) with one in-process fit — the pool
    profile's fast path to a publishable state.  ``pool_replicas`` /
    ``plane_series`` of 0 disable the pool and data-plane stages."""

    name: str
    series: int
    days: int
    chunk: int
    max_iters: int
    phase1_iters: int
    stream_series: int
    stream_batches: int
    loadgen_requests: int
    serve_queue: int
    probe_accelerator: bool      # arm wedged-client (real probe loop)
    recovery_budget_s: float
    run_orchestrate: bool = True
    run_streaming: bool = True
    pool_replicas: int = 0
    pool_requests: int = 0
    plane_series: int = 0
    plane_shard_rows: int = 16
    resident_series: int = 0
    resident_chunk: int = 8
    refit_series: int = 0
    refit_chunk: int = 8
    refit_churn: float = 0.25
    # Loop-storm (the always-on scheduler): reuses refit_series/
    # refit_chunk/refit_churn sizing; the flag arms the kill chain.
    sched_storm: bool = False
    # Storage fault domain (tsspark_tpu.io): ENOSPC/EIO/short-write/
    # lost-fsync against the registry's durable writes plus the
    # disk-pressure brownout driving the degradation ladder.
    storage_storm: bool = False
    # Forecast-plane fault domain (serve/fplane.py): a publisher killed
    # mid-plane (between column writes, sentinel never landed) — the
    # engine must keep serving bitwise-correct forecasts through its
    # compute path and a retry publish must land identical bytes.
    fplane_storm: bool = False
    # Quantile-plane fault domain (uncertainty/qplane.py): a publisher
    # killed mid quantile-column publish (spec landed, CRC sentinel
    # never did) — interval reads must shed to the compute path with
    # bitwise-identical answers and a retry must verify clean.
    qplane_storm: bool = False
    # Alert-stream fault domain (tsspark_tpu.alerts): the scorer child
    # killed mid-publish and mid-delivery, a sink brownout opening the
    # breaker, and a torn certified record — all judged by the
    # alerts_exactly_once invariant (every key delivered exactly once).
    alerts_storm: bool = False


PROFILES: Dict[str, StormProfile] = {
    # Small storm for the tier-1 smoke: one worker kill, everything on
    # CPU, sized to finish in seconds once compile caches are warm.
    "smoke": StormProfile(
        name="smoke", series=16, days=64, chunk=8, max_iters=20,
        phase1_iters=0, stream_series=2, stream_batches=2,
        loadgen_requests=24, serve_queue=16, probe_accelerator=False,
        recovery_budget_s=90.0,
    ),
    # Pool + data-plane smoke for tier-1 (<30 s budget): skips the
    # orchestrate/streaming/serve stages (a direct in-process fit feeds
    # the registry) and drives ONLY the replica pool and columnar
    # data-plane fault classes.
    "pool": StormProfile(
        name="pool", series=12, days=64, chunk=8, max_iters=20,
        phase1_iters=0, stream_series=0, stream_batches=0,
        loadgen_requests=0, serve_queue=16, probe_accelerator=False,
        recovery_budget_s=60.0, run_orchestrate=False,
        run_streaming=False, pool_replicas=2, pool_requests=30,
        plane_series=48, plane_shard_rows=16,
    ),
    # Storage fault-domain smoke for tier-1 (<30 s budget): one
    # in-process fit feeds a private registry, then the five storage
    # classes run against the durable-I/O layer — no pool, no loadgen,
    # no streaming.
    "storage": StormProfile(
        name="storage", series=12, days=48, chunk=8, max_iters=15,
        phase1_iters=0, stream_series=0, stream_batches=0,
        loadgen_requests=0, serve_queue=16, probe_accelerator=False,
        recovery_budget_s=60.0, run_orchestrate=False,
        run_streaming=False, storage_storm=True,
    ),
    # Alert-stream fault-domain smoke for tier-1 (<30 s budget): one
    # in-process fit feeds a private registry + plane, then the three
    # alert classes run against a live AlertStream — scorer child kills
    # at both fault points, a sink brownout, and a torn record — with
    # the alerts_exactly_once invariant judging the sink's final state.
    "alerts": StormProfile(
        name="alerts", series=12, days=48, chunk=8, max_iters=15,
        phase1_iters=0, stream_series=0, stream_batches=0,
        loadgen_requests=0, serve_queue=16, probe_accelerator=False,
        recovery_budget_s=60.0, run_orchestrate=False,
        run_streaming=False, alerts_storm=True,
    ),
    # The acceptance storm (python -m tsspark_tpu.chaos --seed 0):
    # two-phase orchestrate, probe loop included, longer loadgen, the
    # replica pool under kill/split-brain/front-crash, the data plane
    # under torn-shard/driver-kill, and the mesh-resident fit program
    # killed mid-flush.
    "full": StormProfile(
        name="full", series=32, days=96, chunk=8, max_iters=40,
        phase1_iters=6, stream_series=3, stream_batches=3,
        loadgen_requests=160, serve_queue=24, probe_accelerator=True,
        recovery_budget_s=150.0, pool_replicas=2, pool_requests=48,
        plane_series=64, plane_shard_rows=16,
        resident_series=32, resident_chunk=8,
        refit_series=32, refit_chunk=8, refit_churn=0.25,
        sched_storm=True, storage_storm=True, fplane_storm=True,
        qplane_storm=True, alerts_storm=True,
    ),
}


@dataclasses.dataclass(frozen=True)
class StormPlan:
    """The composed storm: every injection, in a deterministic order
    (env-plan rows keep their FaultPlan rule ids by position)."""

    seed: int
    profile: StormProfile
    injections: Tuple[Injection, ...]

    def env_injections(self) -> List[Injection]:
        return [i for i in self.injections if i.mode != "direct"]

    def direct(self, cls: str) -> Optional[Injection]:
        for i in self.injections:
            if i.cls == cls and i.mode == "direct":
                return i
        return None

    def by_class(self) -> Dict[str, List[Injection]]:
        out: Dict[str, List[Injection]] = {}
        for i in self.injections:
            out.setdefault(i.cls, []).append(i)
        return out

    def build_fault_plan(self, state_dir: str) -> Tuple[FaultPlan,
                                                        Dict[str, str]]:
        """(FaultPlan, {rule_id: fault class}) for the env-plan rows.
        Rule ids are positional (``r<i>_<point>``), so the id->class map
        is exact — the MTTR scan reads firing times off the rule ids'
        claim files."""
        plan = FaultPlan(state_dir=state_dir)
        rule_cls: Dict[str, str] = {}
        for inj in self.env_injections():
            plan.fail(
                inj.point, attempts=inj.attempts, after=inj.after,
                mode=inj.mode, series=inj.series, rc=inj.rc,
                delay_s=inj.delay_s or 0.5,
                # The class rides the rule: a firing's span-ledger event
                # then carries it, so MTTR is derivable from the trace
                # alone (obs.ledger.derive_mttr).
                tag=inj.cls,
            )
            rule_cls[plan.rules[-1]["id"]] = inj.cls
        return plan, rule_cls

    def schedule(self) -> List[Dict]:
        """JSON-able schedule (the scorecard's reproducibility record)."""
        return [i.to_dict() for i in self.injections]


def compose(seed: int, profile: str = "full") -> StormPlan:
    """Compose the storm for ``(seed, profile)``.  Pure function of its
    arguments: every parameter below comes from one string-seeded RNG,
    so replays schedule identical injections."""
    prof = PROFILES[profile]
    rng = random.Random(f"tsspark-chaos:{seed}:{profile}")
    inj: List[Injection] = []

    # -- orchestrate stage (env plan; children inherit it) ------------
    if prof.run_orchestrate:
        n_chunks = max(1, prof.series // prof.chunk)
        inj.append(Injection(
            cls="worker-kill", stage="orchestrate",
            point="fit_worker_chunk",
            mode="exit", after=rng.randrange(0, max(1, n_chunks - 1)),
            attempts=1, rc=rng.choice((17, 23, 29)),
        ))
        inj.append(Injection(
            cls="torn-artifact", stage="orchestrate", point="chunk_save",
            mode="corrupt", series=rng.randrange(prof.series),
            attempts=1,
        ))
        inj.append(Injection(
            cls="spawn-fail", stage="orchestrate", point="worker_spawn",
            mode="flag", after=0, attempts=1,
        ))
        inj.append(Injection(
            cls="slow-io", stage="orchestrate", point="fit_chunk",
            mode="sleep", after=rng.randrange(0, n_chunks), attempts=1,
            delay_s=round(rng.uniform(0.2, 0.6), 3),
        ))
        if prof.probe_accelerator:
            inj.append(Injection(
                cls="wedged-client", stage="orchestrate",
                point="device_probe",
                mode="flag", after=0, attempts=rng.choice((1, 2)),
            ))

    # -- registry stage (corruption via the exempt fault machinery) ---
    inj.append(Injection(
        cls="registry-corrupt", stage="registry",
        point=REGISTRY_SNAPSHOT_POINT, mode="corrupt", attempts=1,
    ))
    inj.append(Injection(
        cls="snapshot-torn-shard", stage="registry",
        point="snapshot_plane_shard", mode="direct",
        series=rng.randrange(1 << 16),  # picks the torn shard/rows
    ))

    # -- streaming stage ----------------------------------------------
    if prof.run_streaming:
        inj.append(Injection(
            cls="stream-fault", stage="streaming", point="stream_poll",
            mode="raise", after=rng.randrange(0, 2),
            attempts=rng.choice((1, 2)),
        ))

    # -- serve stage --------------------------------------------------
    if prof.loadgen_requests:
        # serve-fault sizing opens the dispatch breaker deliberately:
        # the engine retries each dispatch twice (harness policy), the
        # breaker threshold is 3, so 6 armed raise-slots = exactly 3
        # failed dispatches = the breaker opens on the last one, then
        # the storm watches it recover through half-open.
        fault_start = rng.randrange(4, 8)
        inj.append(Injection(
            cls="serve-fault", stage="serve", point="serve_predict",
            mode="raise", after=fault_start, attempts=6,
        ))
        third = max(4, prof.loadgen_requests // 3)
        inj.append(Injection(
            cls="queue-overload", stage="serve", point="submit-burst",
            mode="direct", at_request=rng.randrange(2, third),
        ))
        inj.append(Injection(
            cls="activation-race", stage="serve",
            point="publish-activate",
            mode="direct",
            at_request=rng.randrange(2 * third,
                                     prof.loadgen_requests - 2),
        ))

    # -- pool stage (direct injections at request indices; the slot a
    # -- kill/stall targets rides the ``series`` field) ---------------
    if prof.pool_replicas:
        n = prof.pool_requests
        third = max(3, n // 3)
        inj.append(Injection(
            cls="replica-kill", stage="pool", point="replica-proc",
            mode="direct", at_request=rng.randrange(2, third),
            series=rng.randrange(prof.pool_replicas),
        ))
        inj.append(Injection(
            cls="front-crash", stage="pool", point="pool-front",
            mode="direct",
            at_request=rng.randrange(third, 2 * third),
        ))
        inj.append(Injection(
            cls="split-brain-activation", stage="pool",
            point="replica-lease", mode="direct",
            at_request=rng.randrange(2 * third, max(n - 1,
                                                    2 * third + 1)),
            series=rng.randrange(prof.pool_replicas),
        ))

    # -- mesh-resident stage (env plan; the resident child inherits) --
    if prof.resident_series:
        n_waves = max(1, prof.resident_series // prof.resident_chunk)
        inj.append(Injection(
            cls="resident-kill", stage="resident",
            point="resident_flush", mode="exit",
            after=rng.randrange(0, max(1, n_waves - 1)), attempts=1,
            rc=rng.choice((17, 23, 29)),
        ))

    # -- delta-refit stage (the harness arms the child's PRIVATE fault
    # -- plan at the delta_publish point; ``after`` picks which
    # -- copy-forward column write the kill lands between) ------------
    if prof.refit_series:
        inj.append(Injection(
            cls="refit-kill", stage="refit", point="delta_publish",
            mode="direct", after=rng.randrange(2, 8),
            rc=rng.choice((17, 23, 29)),
        ))

    # -- loop-storm stage (the harness arms each kill in the scheduler
    # -- child's PRIVATE plan; the chain resumes ONE pinned plan
    # -- through every stage, then a raw SIGKILL lands mid-cycle) -----
    if prof.sched_storm and prof.refit_series:
        # Wave count of the CHANGED set, not the fleet: a scheduler
        # cycle fits only round(churn * series) rows, so an `after`
        # drawn from the full-fleet wave count would usually outlive
        # the cycle and the armed kill would never fire.
        n_changed = max(1, int(round(prof.refit_churn
                                     * prof.refit_series)))
        churn_waves = max(1, -(-n_changed // prof.refit_chunk))
        for point, after_hi in (("sched_detect", 1),
                                ("resident_flush", churn_waves),
                                ("delta_publish", 8),
                                ("sched_flip", 1)):
            inj.append(Injection(
                cls="loop-storm", stage="sched", point=point,
                mode="direct",
                after=rng.randrange(0, after_hi)
                if point != "delta_publish"
                else rng.randrange(2, after_hi),
                rc=rng.choice((17, 23, 29)),
            ))
        inj.append(Injection(
            cls="loop-storm", stage="sched", point="sched_proc",
            mode="direct",
        ))

    # -- storage fault-domain stage (the harness arms each class's
    # -- PRIVATE plan against the io_* points; ``after`` picks which
    # -- column write the ENOSPC lands on, ``series`` seeds the
    # -- short-write fraction draw, ``rc`` the lost-fsync kill) -------
    if prof.storage_storm:
        inj.append(Injection(
            cls="enospc-mid-publish", stage="storage",
            point="io_write", mode="direct",
            after=rng.randrange(0, 3),
        ))
        inj.append(Injection(
            cls="eio-on-flip", stage="storage", point="io_write",
            mode="direct",
        ))
        inj.append(Injection(
            cls="short-write-torn-column", stage="storage",
            point="io_write", mode="direct",
            series=rng.randrange(1 << 16),
        ))
        inj.append(Injection(
            cls="lost-fsync-then-kill", stage="storage",
            point="io_fsync", mode="direct",
            rc=rng.choice((17, 23, 29)),
        ))
        inj.append(Injection(
            cls="disk-pressure-brownout", stage="storage",
            point="disk-budget", mode="direct",
        ))

    # -- forecast-plane stage (the harness arms the publisher child's
    # -- PRIVATE plan at the fplane_publish point; ``after`` picks
    # -- which column write the kill lands between — the default hot
    # -- ladder publishes 12 columns, so the tear always lands after
    # -- the spec and before the sentinel) ----------------------------
    if prof.fplane_storm:
        inj.append(Injection(
            cls="torn-forecast-plane", stage="fplane",
            point="fplane_publish", mode="direct",
            after=rng.randrange(1, 11),
            rc=rng.choice((17, 23, 29)),
        ))

    # -- quantile-plane stage (same shape at the qplane_publish point;
    # -- the default publish is 3 buckets x 3 quantiles = 9 columns, so
    # -- the tear always lands after the spec and before the sentinel) -
    if prof.qplane_storm:
        inj.append(Injection(
            cls="torn-quantile-plane", stage="qplane",
            point="qplane_publish", mode="direct",
            after=rng.randrange(1, 9),
            rc=rng.choice((17, 23, 29)),
        ))

    # -- alert-stream stage (the harness arms the scorer child's
    # -- PRIVATE plan: ``after`` picks which alert_publish injection
    # -- site the first kill lands on — 0 tears before the record,
    # -- 1 between record and sentinel, 2 after the sentinel — and
    # -- which sink emit the delivery kill lands on; ``series`` seeds
    # -- the torn-record byte pick, ``attempts`` the brownout
    # -- window) ------------------------------------------------------
    if prof.alerts_storm:
        inj.append(Injection(
            cls="alert-scorer-kill", stage="alerts",
            point="alert_publish", mode="direct",
            after=rng.randrange(0, 3), rc=rng.choice((17, 23, 29)),
        ))
        inj.append(Injection(
            cls="alert-scorer-kill", stage="alerts",
            point="alert_deliver", mode="direct",
            # after>=1: at least one alert reaches the sink before the
            # kill, so the successor's redelivery MUST dedup.
            after=rng.randrange(1, 4), rc=rng.choice((17, 23, 29)),
        ))
        inj.append(Injection(
            cls="alert-sink-brownout", stage="alerts",
            point="alert_deliver", mode="direct",
            attempts=rng.randrange(4, 9),
        ))
        inj.append(Injection(
            cls="torn-alert-record", stage="alerts",
            point="alert_record", mode="direct",
            series=rng.randrange(1 << 16),
        ))

    # -- data-plane stage ---------------------------------------------
    if prof.plane_series:
        n_shards = max(1, -(-prof.plane_series // prof.plane_shard_rows))
        inj.append(Injection(
            cls="ingest-driver-kill", stage="data",
            point="ingest-driver", mode="direct",
        ))
        inj.append(Injection(
            cls="plane-torn-shard", stage="data", point="plane-shard",
            mode="direct", series=rng.randrange(n_shards),
        ))

    return StormPlan(seed=seed, profile=prof, injections=tuple(inj))
