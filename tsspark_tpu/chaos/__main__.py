"""``python -m tsspark_tpu.chaos`` — run a seeded chaos storm.

Composes the deterministic fault storm for ``--seed``/``--profile``,
drives the full pipeline through it (orchestrate -> registry ->
streaming -> serve loadgen), verifies the invariants, and writes a
``CHAOS_*.json`` scorecard.  Exit code 0 iff every invariant held.

Like the analysis and serve entry points, this pins JAX to CPU before
anything imports it: a chaos run injects its own faults — it must never
block on a genuinely wedged accelerator tunnel (the storm's wedge is
simulated through the probe injection point instead).
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # The mesh-resident fault class needs a multi-device mesh; on a
    # CPU-pinned storm that means the virtual device mesh the test
    # harness and the multichip dry-run force.  Must land in os.environ
    # BEFORE jax initializes — and it is inherited by the storm's child
    # workers, so the resident child sees the same 8 virtual devices.
    from tsspark_tpu.resident import force_virtual_host_mesh

    force_virtual_host_mesh()
    import jax

    jax.config.update("jax_platforms", "cpu")
    # Persistent compile cache, same keying as the orchestrator's child
    # workers: a storm re-runs the same small programs many times.
    from tsspark_tpu.utils.platform import host_cpu_tag

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("TSSPARK_JAX_CACHE") or os.path.join(
            repo_root, f".jax_cache_{host_cpu_tag()}"
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from tsspark_tpu.chaos.harness import (
        run_storm,
        summarize,
        write_scorecard,
    )
    from tsspark_tpu.chaos.storm import PROFILES

    ap = argparse.ArgumentParser(
        prog="python -m tsspark_tpu.chaos",
        description="deterministic chaos storm over the full pipeline "
                    "(docs/RESILIENCE.md)",
    )
    ap.add_argument("--seed", type=int, default=0,
                    help="storm seed; the same seed reproduces the same "
                    "injection schedule")
    ap.add_argument("--profile", choices=sorted(PROFILES),
                    default="full")
    ap.add_argument("--dir", default=None,
                    help="scratch root (default: a temp dir, removed "
                    "afterwards)")
    ap.add_argument("--report", default=None,
                    help="scorecard path (default: CHAOS_<unix>.json)")
    ap.add_argument("--ledger", default=None,
                    help="run-ledger path (default: RUNLEDGER_<unix>"
                    ".json; render with `python -m tsspark_tpu.obs "
                    "report`)")
    ap.add_argument("--keep-scratch", action="store_true",
                    help="keep the storm's scratch dirs for forensics")
    ap.add_argument("--deadline-s", type=float, default=600.0,
                    help="hard wall bound on the orchestrate stages")
    args = ap.parse_args(argv)

    import time

    report = run_storm(
        seed=args.seed, profile=args.profile, scratch=args.dir,
        keep_scratch=args.keep_scratch, deadline_s=args.deadline_s,
        ledger_path=(args.ledger
                     or f"RUNLEDGER_{int(time.time())}.json"),
    )
    out = write_scorecard(report, args.report)
    print(summarize(report))
    print(f"scorecard -> {out}")
    print(f"run ledger -> {report.get('ledger_path')} "
          f"(python -m tsspark_tpu.obs report)")
    rc = 0 if report["ok"] else 1
    # Regression sentinel post-step: the scorecard joins
    # RUNHISTORY.jsonl, and an MTTR regression vs the rolling baseline
    # fails the storm even when every absolute invariant held
    # (docs/OBSERVABILITY.md "Trajectory & SLOs").
    if os.environ.get("TSSPARK_SENTINEL", "1") != "0":
        try:
            from tsspark_tpu.obs import regress

            verdict = regress.sentinel_report(report, source=out)
            if verdict is not None:
                print(regress.summarize(verdict))
                if not verdict["ok"]:
                    rc = rc or 1
        except Exception as e:
            print(f"sentinel skipped: {e!r}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
