"""The chaos harness: drive the full pipeline through a seeded storm.

One ``run_storm`` call fits a synthetic batch through the resilient
orchestrator (subprocess fit workers), publishes the result into a serve
registry, runs the streaming driver over a micro-batch source, and then
load-generates against the prediction engine — with the storm's faults
(``storm.compose``) armed across every stage — while the invariant
checkers (``invariants``) verify that nothing was lost, duplicated,
torn, or slow to recover.  The outcome is a ``CHAOS_*.json`` scorecard
(the robustness analog of ``BENCH_*``/``SERVE_*``): faults injected,
invariants checked, MTTR per fault class, and one overall ``ok``.

Determinism: the schedule is a pure function of ``(seed, profile)``
(recorded verbatim in the scorecard), injection firing is claimed
through the resilience fault harness's cross-process counters, and the
loadgen request mix is derived from the same seed — so a regression in
any recovery path reproduces under the same seed.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import signal
import socket
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from tsspark_tpu.chaos import invariants as inv
from tsspark_tpu.chaos.storm import (
    PROFILES,
    REGISTRY_SNAPSHOT_POINT,
    StormPlan,
    compose,
)
from tsspark_tpu.obs import context as obs
from tsspark_tpu.obs import ledger as obs_ledger
from tsspark_tpu.obs.metrics import DEFAULT as METRICS
from tsspark_tpu.config import (
    ProphetConfig,
    SeasonalityConfig,
    SolverConfig,
)
from tsspark_tpu.resilience import faults
from tsspark_tpu.resilience.policy import CircuitBreaker, RetryPolicy
from tsspark_tpu.utils.atomic import atomic_write

#: Fast schedules for the storm's parent loop: the storm injects the
#: failures, so the recovery machinery must not pad MTTR with
#: production-sized sleeps.
_RETRY = RetryPolicy(max_attempts=9, base_delay_s=0.1, backoff=1.0,
                     max_delay_s=0.1)
_PROBE = RetryPolicy(max_attempts=None, base_delay_s=0.2, backoff=1.5,
                     max_delay_s=1.0, attempt_timeout_s=60.0)


def _synthetic_batch(seed: int, series: int, days: int):
    """Deterministic finite batch: level + trend + weekly cycle."""
    rng = np.random.default_rng(seed)
    t = np.arange(float(days))
    level = rng.uniform(5.0, 50.0, (series, 1))
    slope = rng.uniform(-0.02, 0.05, (series, 1))
    amp = rng.uniform(0.5, 3.0, (series, 1))
    y = (level + slope * t[None, :]
         + amp * np.sin(2 * np.pi * t[None, :] / 7.0)
         + rng.normal(0.0, 0.2, (series, days)))
    return t, y.astype(np.float32)


def _config(max_iters: int):
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=3,
    )
    return cfg, SolverConfig(max_iters=max_iters)


def _direct_forecast(backend, snap, sids, horizon: int):
    """The reference read path: gather the snapshot rows and call
    ``backend.predict`` directly (the parity oracle the engine is pinned
    against in tests/test_serve.py)."""
    idx, _ = snap.rows(sids)
    sub, step = snap.take(idx)
    last = np.asarray(sub.meta.ds_start + sub.meta.ds_span, np.float64)
    grid = last[:, None] + step[:, None] * np.arange(1, horizon + 1)
    out = backend.predict(sub, grid, num_samples=0)
    return grid, {k: np.asarray(v) for k, v in out.items()}


# ---------------------------------------------------------------------------
# stage A: orchestrate under storm
# ---------------------------------------------------------------------------


def _run_orchestrate(scratch: str, name: str, ds, y, cfg, solver,
                     storm: StormPlan, deadline_s: float) -> Dict:
    from tsspark_tpu import orchestrate

    from tsspark_tpu.resilience.integrity import ChunkIntegrityError

    prof = storm.profile
    data_dir = os.path.join(scratch, name, "data")
    out_dir = os.path.join(scratch, name, "out")
    os.makedirs(out_dir, exist_ok=True)
    orchestrate.spill_data(data_dir, ds, y)
    orchestrate.save_run_config(out_dir, cfg, solver)
    t0 = time.time()
    state: Dict = {}
    integrity_rounds = 0
    # Same bounded integrity loop as fit_resilient: a corruption that
    # only surfaces at assembly re-queues its range (quarantined by
    # load_fit_state) and the parent loop refits it.
    while True:
        state = orchestrate.run_resilient(
            data_dir=data_dir, out_dir=out_dir, series=prof.series,
            chunk=prof.chunk, min_chunk=prof.chunk, segment=0,
            phase1_iters=prof.phase1_iters, no_phase1_tune=True,
            deadline=time.time() + deadline_s, reserve=lambda: 5.0,
            progress_timeout=300.0,
            probe_accelerator=prof.probe_accelerator or None,
            retry_policy=_RETRY, probe_policy=_PROBE, state=state,
        )
        if not state.get("complete"):
            break
        try:
            orchestrate.load_fit_state(out_dir, prof.series)
            break
        except ChunkIntegrityError:
            integrity_rounds += 1
            if integrity_rounds > 3:
                raise
            marker = os.path.join(out_dir, "phase2_done")
            if os.path.exists(marker):
                os.remove(marker)
    return {
        "out_dir": out_dir,
        "complete": bool(state.get("complete")),
        "retries": int(state.get("retries", 0)),
        "integrity_rounds": integrity_rounds,
        "probes": state.get("probes"),
        "wall_s": round(time.time() - t0, 3),
    }


# ---------------------------------------------------------------------------
# stage C: streaming driver under storm
# ---------------------------------------------------------------------------


def _run_streaming(registry, cfg, storm: StormPlan, seed: int) -> Dict:
    import pandas as pd

    from tsspark_tpu.streaming.driver import StreamingForecaster
    from tsspark_tpu.streaming.source import InMemorySource

    prof = storm.profile
    rng = np.random.default_rng(seed + 1)
    base = 40
    batches = []
    for b in range(prof.stream_batches):
        rows = []
        for s in range(prof.stream_series):
            lo = base * (b > 0) + 10 * max(0, b - 1)
            n = base if b == 0 else 10
            t = np.arange(lo, lo + n, dtype=float)
            yv = (20.0 + s + 0.05 * t
                  + rng.normal(0.0, 0.1, n))
            rows.append(pd.DataFrame({
                "series_id": f"stream{s}", "ds": t, "y": yv,
            }))
        batches.append(pd.concat(rows, ignore_index=True))
    driver = StreamingForecaster(
        cfg, SolverConfig(max_iters=20), backend="tpu", chunk_size=8,
    )
    breaker = CircuitBreaker(failure_threshold=4, reset_timeout_s=0.2,
                             name="stream-source")
    t0 = time.time()
    stats = driver.run(
        InMemorySource(batches),
        poll_policy=RetryPolicy(max_attempts=4, base_delay_s=0.0,
                                max_delay_s=0.0),
        poll_breaker=breaker,
    )
    version = driver.publish(registry)
    return {
        "wall_s": round(time.time() - t0, 3),
        "micro_batches": stats.micro_batches,
        "series_refit": stats.series_refit,
        "published_version": version,
        "breaker": breaker.snapshot(),
        "end_time": time.time(),
    }


# ---------------------------------------------------------------------------
# stage D: prediction engine under storm
# ---------------------------------------------------------------------------


def _run_serve(registry, ids: List[str], state_v1, storm: StormPlan,
               mttr: Dict[str, Optional[float]]) -> Dict:
    from tsspark_tpu.resilience.faults import FaultInjected
    from tsspark_tpu.serve.engine import (
        BackendUnavailable,
        EngineOverloaded,
        ForecastRequest,
        PredictionEngine,
        ServeError,
    )
    from tsspark_tpu.serve.registry import RegistryError

    prof = storm.profile
    breaker = CircuitBreaker(failure_threshold=3, reset_timeout_s=0.3,
                             name="backend")
    engine = PredictionEngine(
        registry, max_queue=prof.serve_queue, max_batch=16,
        retry_policy=RetryPolicy(max_attempts=2, base_delay_s=0.0,
                                 max_delay_s=0.0),
        breaker=breaker,
        registry_breaker=CircuitBreaker(3, 0.3, name="registry"),
    )
    snaps: Dict[int, object] = {}

    def snap_of(version: int):
        if version not in snaps:
            snaps[version] = registry.load(version, fallback=False)
        return snaps[version]

    counters = {
        "requests": 0, "completed": 0, "failed": 0, "fast_failed": 0,
        "overload_rejected": 0, "parity_checks": 0,
        "parity_failures": [],
    }
    t_first_fail: Optional[float] = None
    t_recovered: Optional[float] = None
    t_race: Optional[float] = None
    race_version: Optional[int] = None

    def check_parity(res, sids, horizon) -> None:
        snap = snap_of(res.version)
        grid, direct = _direct_forecast(engine.backend, snap, sids,
                                        horizon)
        counters["parity_checks"] += 1
        if not np.array_equal(np.asarray(res.ds), grid):
            counters["parity_failures"].append(
                f"ds mismatch v{res.version} {sids} h={horizon}"
            )
        for k, v in direct.items():
            if not np.array_equal(np.asarray(res.values[k]), v):
                counters["parity_failures"].append(
                    f"{k} mismatch v{res.version} {sids} h={horizon}"
                )

    def attempt(sids, horizon, num_samples=0, seed=0, parity=False):
        nonlocal t_first_fail, t_recovered
        counters["requests"] += 1
        try:
            res = engine.forecast(sids, horizon,
                                  num_samples=num_samples, seed=seed,
                                  timeout_s=30.0)
        except BackendUnavailable:
            counters["fast_failed"] += 1
            if t_first_fail is None:
                t_first_fail = time.time()
            return None
        except (ServeError, RegistryError, FaultInjected):
            counters["failed"] += 1
            if t_first_fail is None:
                t_first_fail = time.time()
            return None
        counters["completed"] += 1
        if t_first_fail is not None and t_recovered is None:
            t_recovered = time.time()
        if t_race is not None and "activation-race" not in mttr:
            mttr["activation-race"] = time.time() - t_race
            obs.event("recovered", tag="activation-race")
        if parity and num_samples == 0:
            check_parity(res, sids, horizon)
        return res

    overload = storm.direct("queue-overload")
    race = storm.direct("activation-race")
    t0 = time.time()
    for i in range(prof.loadgen_requests):
        if overload is not None and i == overload.at_request:
            t_burst = time.time()
            # Direct injections never ride the env fault plan, so the
            # harness itself annotates the trace (paired with the
            # "recovered" event below — obs.ledger.derive_mttr reads
            # this class's MTTR straight off the pair).
            obs.event("fault", tag="queue-overload", mode="direct")
            rejected = 0
            pending = []
            for j in range(prof.serve_queue + 8):
                try:
                    pending.append(engine.submit(ForecastRequest.make(
                        [ids[j % len(ids)]], 5,
                    )))
                except EngineOverloaded:
                    rejected += 1
            while engine.pump() > 0:
                pass
            for p in pending:
                try:
                    p.result(0.0)
                except Exception:
                    pass  # storm faults may fail some; counted below
            counters["overload_rejected"] = rejected
            # Recovery: the queue admits again as soon as it drained.
            try:
                ok = engine.submit(ForecastRequest.make([ids[0]], 5))
                while not ok.done():
                    engine.pump()
                mttr["queue-overload"] = time.time() - t_burst
                obs.event("recovered", tag="queue-overload")
            except EngineOverloaded:
                mttr["queue-overload"] = None
        if race is not None and i == race.at_request:
            # Publish + activate mid-loadgen: the activation listener
            # invalidates the cache while dispatches may be in flight —
            # the exact race the engine's stale-insert guard closes.
            race_version = registry.publish(
                state_v1._replace(
                    theta=np.asarray(state_v1.theta) * 1.02
                ),
                ids, step=np.ones(len(ids)),
            )
            t_race = time.time()
            obs.event("fault", tag="activation-race", mode="direct",
                      version=race_version)
        k = 1 + (i % 3)
        sids = [ids[(i * 7 + j * 3) % len(ids)] for j in range(k)]
        res = attempt(sids, (5, 7, 12)[i % 3], parity=(i % 4 == 0))
        if res is None and breaker.state != CircuitBreaker.CLOSED:
            # A well-behaved client honors the breaker's retry-after
            # instead of hammering fast-fails; the storm does too, so
            # the warm loop also exercises the half-open recovery.
            time.sleep(breaker.retry_after_s() + 0.01)

    # Drain the serve-fault window and watch the breaker cycle all the
    # way: guaranteed-miss requests (unique sampling seeds) force a
    # dispatch each round until the armed raise-slots are exhausted, the
    # breaker has opened at least once, and it has closed again through
    # a successful half-open trial.
    extra = 0
    while (t_first_fail is None or t_recovered is None
           or breaker.opens == 0
           or breaker.state != CircuitBreaker.CLOSED) and extra < 80:
        extra += 1
        if breaker.state == CircuitBreaker.OPEN:
            time.sleep(breaker.retry_after_s() + 0.01)
        attempt([ids[extra % len(ids)]], 5, num_samples=1,
                seed=10_000 + extra)
    if t_first_fail is not None:
        mttr["serve-fault"] = (
            None if t_recovered is None else t_recovered - t_first_fail
        )
    # One final deterministic request on the post-race version closes
    # the parity loop across the activation flip.
    attempt([ids[0], ids[1]], 7, parity=True)

    cache_versions = engine.cache.key_versions()
    active = registry.active_version()
    return {
        "wall_s": round(time.time() - t0, 3),
        "counters": {k: v for k, v in counters.items()
                     if k != "parity_failures"},
        "parity_failures": counters["parity_failures"],
        "engine": engine.stats.snapshot(),
        "cache": engine.cache.stats(),
        "breaker": breaker.snapshot(),
        "breaker_opened": breaker.opens > 0,
        "race_version": race_version,
        "cache_key_versions": cache_versions,
        "active_version": active,
        "cache_consistent": all(v == active for v in cache_versions),
    }


# ---------------------------------------------------------------------------
# stage E: replica pool under storm
# ---------------------------------------------------------------------------


def _run_pool(scratch: str, registry, ids: List[str], state_v1,
              storm: StormPlan,
              mttr: Dict[str, Optional[float]]) -> Tuple[Dict, Dict]:
    """Drive the serve replica pool through replica-kill, front-crash,
    and split-brain-activation at the storm's request indices.  Returns
    (stage info, invariants)."""
    import numpy as np

    from tsspark_tpu.serve.pool import (
        NoReplicaAvailable,
        ReplicaPool,
        _send_line,
        shard_of,
    )

    prof = storm.profile
    n = prof.pool_replicas
    pool_dir = os.path.join(scratch, "pool")
    pool = ReplicaPool(pool_dir, registry.root, n_replicas=n,
                       heartbeat_s=0.2, breaker_reset_s=0.3,
                       spawn_timeout_s=180.0)
    t0 = time.time()
    pool.start()
    counters: Dict[str, object] = {
        "requests": 0, "completed": 0, "shed": 0, "failed": 0,
        "fenced_probe_refused": True,
    }
    # Front-side totals accumulated ACROSS the front crash (a successor
    # front starts its own counters; the storm wants storm-wide sums).
    tot = {"failovers": 0, "respawns": 0, "wrong_version": 0,
           "fenced_seen": 0}

    def fold_front(p) -> None:
        tot["failovers"] += p.failovers
        tot["respawns"] += p.respawns
        tot["wrong_version"] += p.wrong_version
        tot["fenced_seen"] += p.fenced_seen
    kill = storm.direct("replica-kill")
    crash = storm.direct("front-crash")
    split = storm.direct("split-brain-activation")
    t_kill: Optional[float] = None
    kill_slot: Optional[int] = None
    kill_probe_sid: Optional[str] = None
    front_same_pids: Optional[bool] = None
    split_info: Dict = {}

    def attempt(sids, horizon):
        counters["requests"] += 1
        try:
            resp = pool.forecast(sids, horizon)
        except NoReplicaAvailable:
            counters["failed"] += 1
            return None
        if resp.get("ok"):
            counters["completed"] += 1
            return resp
        reason = (resp.get("error") or {}).get("reason")
        if reason == "deadline-exceeded":
            counters["shed"] += 1
        else:
            counters["failed"] += 1
        return None

    def zombie_probe(sock_path: str, expect: int) -> bool:
        """Ask the revived zombie directly on its OLD socket: it must
        refuse with a structured error (or be gone), never serve."""
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(15.0)
            s.connect(sock_path)
            _send_line(s, {"id": "zprobe", "series_ids": [ids[0]],
                           "horizon": 5, "expect_version": expect})
            buf = b""
            while b"\n" not in buf:
                chunk = s.recv(65536)
                if not chunk:
                    return True  # closed without serving: safe
                buf += chunk
            s.close()
            resp = json.loads(buf.split(b"\n", 1)[0])
            return (not resp.get("ok")) and (
                (resp.get("error") or {}).get("reason")
                in ("fenced", "version-mismatch")
            )
        except OSError:
            return True  # zombie already exited: equally safe

    def run_split_brain() -> None:
        zslot = split.series % n
        zpid = pool.replicas[zslot].pid
        zsock = pool.replicas[zslot].socket_path
        obs.event("fault", tag="split-brain-activation", mode="direct",
                  slot=zslot, pid=zpid)
        t_split = time.time()
        os.kill(zpid, signal.SIGSTOP)
        try:
            replaced = False
            deadline = time.time() + 60.0
            while time.time() < deadline:
                if zslot in pool.ensure_alive():
                    replaced = True
                    break
                time.sleep(0.1)
            v_new = registry.publish(
                state_v1._replace(
                    theta=np.asarray(state_v1.theta) * 1.02
                ),
                ids, step=np.ones(len(ids)), activate=False,
            )
            pool.activate(v_new, hot_series=ids[:8], horizons=(5, 7))
        finally:
            try:
                os.kill(zpid, signal.SIGCONT)
            except OSError:
                pass
        time.sleep(0.3)
        counters["fenced_probe_refused"] = zombie_probe(
            zsock, pool.expected_version
        )
        # Recovery: the replaced slot serves the NEW version.
        recovered = None
        deadline = time.time() + 30.0
        while time.time() < deadline:
            resp = attempt([_owned_sid(zslot)], 5)
            if (resp is not None and resp.get("replica") == zslot
                    and resp.get("version") == v_new):
                recovered = time.time() - t_split
                break
            pool.ensure_alive()
            time.sleep(0.1)
        mttr["split-brain-activation"] = recovered
        if recovered is not None:
            obs.event("recovered", tag="split-brain-activation")
        split_info.update({
            "slot": zslot, "zombie_pid": zpid, "replaced": replaced,
            "activated_version": v_new,
            "fenced_probe_refused": counters["fenced_probe_refused"],
        })

    def _owned_sid(slot: int) -> str:
        for s in ids:
            if shard_of(s, n) == slot:
                return s
        return ids[0]

    for i in range(prof.pool_requests):
        if kill is not None and i == kill.at_request:
            kill_slot = kill.series % n
            kill_probe_sid = _owned_sid(kill_slot)
            obs.event("fault", tag="replica-kill", mode="direct",
                      slot=kill_slot)
            t_kill = time.time()
            os.kill(pool.replicas[kill_slot].pid, signal.SIGKILL)
            # The failover acceptance is not vacuous: a request AT the
            # dead slot's shard, before any respawn, must be served by
            # the sibling.
            resp = attempt([kill_probe_sid], 5)
            counters["failover_exercised"] = (
                resp is not None and resp.get("replica") != kill_slot
            )
        if crash is not None and i == crash.at_request:
            obs.event("fault", tag="front-crash", mode="direct")
            t_crash = time.time()
            before = {k: info.pid for k, info in pool.replicas.items()
                      if pool._slot_unhealthy(info) is None}
            fold_front(pool)
            pool.close_front()
            pool = ReplicaPool.attach(pool_dir, heartbeat_s=0.2,
                                      breaker_reset_s=0.3,
                                      spawn_timeout_s=180.0)
            front_same_pids = all(
                pool.replicas[k].pid == pid
                for k, pid in before.items()
            )
            resp = attempt([ids[i % len(ids)]], 5)
            if resp is not None:
                mttr["front-crash"] = time.time() - t_crash
                obs.event("recovered", tag="front-crash")
        if split is not None and i == split.at_request:
            run_split_brain()
        k = 1 + (i % 2)
        attempt([ids[(i * 5 + j * 3) % len(ids)] for j in range(k)],
                (5, 7)[i % 2])
        if (t_kill is not None and "replica-kill" not in mttr):
            # Recovery: the killed slot itself answers again (sibling
            # failover alone does not count as the slot recovering).
            pool.ensure_alive()
            resp = attempt([kill_probe_sid], 5)
            if resp is not None and resp.get("replica") == kill_slot:
                mttr["replica-kill"] = time.time() - t_kill
                obs.event("recovered", tag="replica-kill")

    pool.ensure_alive()
    stats = pool.stats()
    fold_front(pool)
    counters["wrong_version"] = tot["wrong_version"]
    replica_pids = {
        k: (stats["replicas"].get(str(k)) or {}).get("pid")
        for k in range(n)
    }
    invariants = {
        "pool_failover": inv.pool_request_integrity(counters),
        "pool_single_owner": inv.pool_single_owner(pool_dir,
                                                   replica_pids),
        "pool_front_reattach": {
            "ok": front_same_pids is not False,
            "live_replicas_adopted": front_same_pids,
        },
    }
    stage = {
        "wall_s": round(time.time() - t0, 3),
        "counters": {k: v for k, v in counters.items()},
        "failovers": tot["failovers"],
        "respawns": tot["respawns"],
        "fenced_seen": tot["fenced_seen"],
        "split_brain": split_info,
        "per_replica": stats["replicas"],
        "expected_version": pool.expected_version,
    }
    pool.stop()
    return stage, invariants


# ---------------------------------------------------------------------------
# stage F: columnar data plane under storm
# ---------------------------------------------------------------------------


def _run_plane(scratch: str, storm: StormPlan,
               mttr: Dict[str, Optional[float]]) -> Tuple[Dict, Dict]:
    """Data-plane fault classes: the background ingest driver is killed
    mid-fill (the consumer self-produces the holes — block-seeded, so
    bitwise the same bytes), then a landed shard is torn under its
    sentinel (verify must reject, repair must re-land)."""
    import numpy as np

    from tsspark_tpu.data import ingest as data_ingest
    from tsspark_tpu.data import plane

    prof = storm.profile
    root = os.path.join(scratch, "plane")
    os.makedirs(root, exist_ok=True)
    spec = plane.DatasetSpec(
        generator="demo_weekly", n_series=prof.plane_series,
        n_timesteps=48, seed=storm.seed,
        shard_rows=prof.plane_shard_rows,
    )
    t0 = time.time()

    # ---- ingest-driver-kill + self-produce-on-stall ------------------
    driver = data_ingest.IngestDriver.start(spec, root=root, processes=1)
    dset_dir = driver.dataset_dir
    obs.event("fault", tag="ingest-driver-kill", mode="direct")
    t_kill = time.time()
    driver.kill()
    driver.wait(10.0)
    landed_at_kill = plane.landed_ranges(dset_dir)
    self_produced = 0
    while plane.ingest_pending(dset_dir):
        if not plane.produce_next_missing(dset_dir):
            break
        self_produced += 1
    if not plane.is_complete(dset_dir):
        plane.finalize(spec, root)
    mttr["ingest-driver-kill"] = time.time() - t_kill
    obs.event("recovered", tag="ingest-driver-kill")

    # ---- plane-torn-shard: corrupt landed rows under their sentinel --
    torn = storm.direct("plane-torn-shard")
    ranges = plane.shard_ranges(spec)
    lo, hi = ranges[(torn.series or 0) % len(ranges)]
    obs.event("fault", tag="plane-torn-shard", mode="direct",
              lo=lo, hi=hi)
    t_torn = time.time()
    mm = np.lib.format.open_memmap(os.path.join(dset_dir, "y.npy"),
                                   mode="r+")
    mm[lo:hi].view(np.uint32)[...] ^= np.uint32(0x5A5A5A5A)
    mm.flush()
    del mm
    torn_detected = not plane.verify_shard(dset_dir, lo, hi)
    repaired = plane.repair(spec, root=root)
    mttr["plane-torn-shard"] = time.time() - t_torn
    obs.event("recovered", tag="plane-torn-shard")

    plane_inv = inv.plane_consistent(spec, root)
    plane_inv["torn_detected"] = torn_detected
    plane_inv["repaired_ranges"] = [list(r) for r in repaired]
    if not torn_detected:
        plane_inv["ok"] = False
        plane_inv.setdefault("errors", []).append(
            "verify_shard accepted the torn shard"
        )
    if [lo, hi] not in plane_inv["repaired_ranges"]:
        plane_inv["ok"] = False
        plane_inv.setdefault("errors", []).append(
            f"repair did not re-land the torn shard [{lo}, {hi})"
        )
    stage = {
        "wall_s": round(time.time() - t0, 3),
        "n_shards": len(ranges),
        "landed_at_kill": [list(r) for r in landed_at_kill],
        "self_produced": self_produced,
        "torn_shard": [lo, hi],
    }
    return stage, {"plane_consistent": plane_inv}


# ---------------------------------------------------------------------------
# stage G: mesh-resident fit program under storm
# ---------------------------------------------------------------------------


def _run_resident_storm(scratch: str, storm: StormPlan,
                        deadline_s: float) -> Tuple[Dict, Dict]:
    """The resident-kill class: the mesh-resident fit child (orchestrate
    ``--_resident``) dies at the armed ``resident_flush`` point mid
    flush-stream; a successor invocation must resume from the last
    LANDED checkpoint flush and finish with exactly-once coverage,
    bitwise equal to a fault-free reference run."""
    import glob as glob_mod

    from tsspark_tpu import orchestrate, resident

    prof = storm.profile
    cfg, solver = _config(prof.max_iters)
    ds, y = _synthetic_batch(storm.seed + 11, prof.resident_series,
                             prof.days)
    base = os.path.join(scratch, "resident")
    data_dir = os.path.join(base, "data")
    out_dir = os.path.join(base, "out")
    os.makedirs(out_dir, exist_ok=True)
    orchestrate.spill_data(data_dir, ds, y)
    orchestrate.save_run_config(out_dir, cfg, solver)
    extra = [
        "--lo", "0", "--hi", str(prof.resident_series),
        "--chunk", str(prof.resident_chunk),
        "--series", str(prof.resident_series),
        "--phase1-iters", str(prof.phase1_iters), "--no-phase1-tune",
    ]
    t0 = time.time()
    rc_first = orchestrate.spawn_worker(
        "--_resident", data_dir, out_dir, extra,
        timeout=deadline_s, progress_timeout=300.0,
    )
    landed_at_kill = orchestrate.completed_ranges(out_dir)
    marker = os.path.join(out_dir, "phase2_done")
    attempts = 1
    rc = rc_first
    while (orchestrate.missing_ranges(
            orchestrate.completed_ranges(out_dir), prof.resident_series)
           or not os.path.exists(marker)) and attempts < 5:
        attempts += 1
        rc = orchestrate.spawn_worker(
            "--_resident", data_dir, out_dir, extra,
            timeout=deadline_s, progress_timeout=300.0,
        )
    t_end = time.time()
    complete = rc == 0 and not orchestrate.missing_ranges(
        orchestrate.completed_ranges(out_dir), prof.resident_series
    ) and os.path.exists(marker)
    got = orchestrate.load_fit_state(out_dir, prof.resident_series)
    # The resident flush-state artifact is the proof the MESH path ran
    # (a meshless child would have degraded to the chunk workers and
    # passed vacuously).
    res_state_path = os.path.join(out_dir, resident.RESIDENT_STATE_FILE)
    ran_resident = os.path.exists(res_state_path)

    # Fault-free reference, file-protocol path, faults disarmed: bitwise
    # equality doubles as the chaos-level resident/fileproto parity gate.
    env_plan = os.environ.pop(faults.ENV_VAR, None)
    try:
        ref_out = os.path.join(base, "ref_out")
        os.makedirs(ref_out, exist_ok=True)
        orchestrate.save_run_config(ref_out, cfg, solver)
        ref_state = orchestrate.run_resilient(
            data_dir=data_dir, out_dir=ref_out,
            series=prof.resident_series, chunk=prof.resident_chunk,
            min_chunk=prof.resident_chunk, segment=0,
            phase1_iters=prof.phase1_iters, no_phase1_tune=True,
            deadline=time.time() + deadline_s, reserve=lambda: 5.0,
            progress_timeout=300.0, probe_accelerator=False,
            retry_policy=_RETRY, probe_policy=_PROBE,
        )
        ref = orchestrate.load_fit_state(ref_out, prof.resident_series)
    finally:
        if env_plan is not None:
            os.environ[faults.ENV_VAR] = env_plan

    inv_res = inv.coverage_exactly_once(
        orchestrate.completed_ranges(out_dir), prof.resident_series
    )
    bitwise = inv.states_bitwise_equal(got, ref)
    inv_res["bitwise_vs_fileproto_reference"] = bitwise
    inv_res["ok"] &= bitwise["ok"] and complete and ran_resident
    if not complete:
        inv_res.setdefault("errors", []).append(
            "resident run never completed its coverage after resume"
        )
    if not ran_resident:
        inv_res.setdefault("errors", []).append(
            "no resident flush-state artifact: the mesh path never ran "
            "(meshless fallback would make this class vacuous)"
        )
    stage = {
        "wall_s": round(t_end - t0, 3),
        "rc_first": rc_first,
        "attempts": attempts,
        "landed_at_kill": [list(r) for r in landed_at_kill],
        "ran_resident": ran_resident,
        "complete": complete,
        "ref_complete": bool(ref_state.get("complete")),
        "chunks": len(glob_mod.glob(
            os.path.join(out_dir, "chunk_*.npz")
        )),
    }
    return stage, {"resident_exactly_once": inv_res}


# ---------------------------------------------------------------------------
# stage H: delta-refit engine under storm
# ---------------------------------------------------------------------------


def _run_refit_storm(scratch: str, storm: StormPlan,
                     mttr: Dict[str, Optional[float]],
                     deadline_s: float) -> Tuple[Dict, Dict]:
    """The refit-kill class: a delta lands on the data plane, a
    delta-refit child (``python -m tsspark_tpu.refit``) runs the warm
    waves, and an armed ``delta_publish`` exit fault kills it MID
    DELTA-PUBLISH (copy-forward columns half-written, manifest never
    updated).  Invariants: the pool serves only the last complete
    version throughout (zero wrong-version), the in-process successor
    resumes from the landed chunk flushes (zero refit dispatches) and
    re-publishes, and the final snapshot's unchanged rows are bitwise
    the prior active version's.

    Runs with the STORM env plan popped: the stage's only fault is the
    child's PRIVATE plan — the successor's in-process resident waves
    must not consume (or fire!) the resident-kill rule's claims, and an
    exit fault firing in-process would kill the harness itself."""
    import subprocess

    from tsspark_tpu import orchestrate, refit, resident
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.pool import ReplicaPool
    from tsspark_tpu.serve.registry import ParamRegistry

    prof = storm.profile
    base = os.path.join(scratch, "refit")
    cfg, solver = _config(prof.max_iters)
    t0 = time.time()
    env_plan = os.environ.pop(faults.ENV_VAR, None)
    pool = None
    try:
        # ---- setup: plane dataset (private root — deltas mutate
        # ---- landed rows), cold resident fit, publish v1, pool ------
        spec = plane.DatasetSpec(
            generator="demo_weekly", n_series=prof.refit_series,
            n_timesteps=64, seed=storm.seed,
            shard_rows=prof.plane_shard_rows,
        )
        dset = plane.ensure(spec, root=os.path.join(base, "plane"))
        ids = plane.series_ids(spec)
        out_dir = os.path.join(base, "out")
        os.makedirs(out_dir, exist_ok=True)
        orchestrate.save_run_config(out_dir, cfg, solver)
        resident.run_resident(
            data_dir=dset, out_dir=out_dir, series=prof.refit_series,
            chunk=prof.refit_chunk, phase1_iters=0, no_phase1_tune=True,
        )
        registry = ParamRegistry(os.path.join(base, "registry"), cfg)
        v1 = orchestrate.publish_fit_state(
            registry, out_dir, ids, step=np.ones(prof.refit_series),
            data_stamp=plane.delta_seq(dset),
        )
        pool = ReplicaPool(os.path.join(base, "pool"), registry.root,
                           n_replicas=max(2, prof.pool_replicas),
                           heartbeat_s=0.2, breaker_reset_s=0.3,
                           spawn_timeout_s=180.0)
        pool.start()
        first = pool.forecast([str(ids[0])], 5)
        assert first.get("ok") and first.get("version") == v1, first

        delta_rec = plane.land_synthetic_delta(dset, prof.refit_churn)

        # ---- the kill: refit child with delta_publish armed ---------
        inj = storm.direct("refit-kill")
        child_plan = faults.FaultPlan(
            state_dir=os.path.join(base, "faults")
        )
        child_plan.fail("delta_publish", attempts=1, after=inj.after,
                        mode="exit", rc=inj.rc, tag="refit-kill")
        env = orchestrate._child_env()
        env[faults.ENV_VAR] = child_plan.to_env()
        obs.inject_env(env)
        refit_scratch = os.path.join(base, "refit_scratch")
        cmd = [sys.executable, "-m", "tsspark_tpu.refit",
               "--data", dset, "--registry", registry.root,
               "--scratch", refit_scratch,
               "--chunk", str(prof.refit_chunk),
               "--max-iters", str(prof.max_iters), "--no-activate"]
        child = subprocess.run(cmd, env=env, stdout=sys.stderr,
                               timeout=deadline_s)
        t_fault = time.time()
        obs.event("fault", tag="refit-kill", mode="direct",
                  rc=child.returncode)
        fired = inv.fault_firing_times(
            child_plan.state_dir,
            {child_plan.rules[0]["id"]: "refit-kill"},
            child_plan.rules,
        ).get("refit-kill", [])

        # ---- mid-kill probes: only the last COMPLETE version serves -
        active_after_kill = registry.active_version()
        probe = pool.forecast([str(ids[0])], 5)
        probe_ok = bool(probe.get("ok")
                        and probe.get("version") == v1)

        # ---- successor: resume from landed flushes, publish, flip ---
        res = refit.run_refit(
            data_dir=dset, registry=registry, scratch=refit_scratch,
            chunk=prof.refit_chunk, solver_config=solver,
            warm_start=True, pool=pool,
            hot_series=[str(s) for s in ids[:8]], horizons=(5, 7),
        )
        v2 = res.get("version")
        recovered = None
        deadline = time.time() + 30.0
        while v2 is not None and time.time() < deadline:
            resp = pool.forecast([str(ids[1])], 5)
            if resp.get("ok") and resp.get("version") == v2:
                recovered = time.time() - t_fault
                break
            pool.ensure_alive()
            time.sleep(0.1)
        mttr["refit-kill"] = recovered
        if recovered is not None:
            obs.event("recovered", tag="refit-kill")

        # ---- invariants (an incomplete successor must FAIL the
        # ---- invariant, never crash the storm report) ---------------
        v1_dir = registry.version_dir(v1)
        if v2 is not None:
            info = registry.delta_info(v2) or {}
            bitwise = inv.refit_unchanged_bitwise(
                v1_dir, registry.version_dir(v2),
                info.get("changed_rows") or (),
            )
        else:
            bitwise = {"ok": False,
                       "errors": ["successor published no version"]}
        wrong_version = pool.wrong_version
        inv_refit = {
            "ok": (child.returncode != 0 and len(fired) == 1
                   and active_after_kill == v1 and probe_ok
                   and wrong_version == 0
                   and bool(res.get("complete"))
                   and res.get("fit_dispatches") == 0
                   and recovered is not None and bitwise["ok"]),
            "child_rc": child.returncode,
            "fault_fired": len(fired),
            "active_after_kill": active_after_kill,
            "served_v1_after_kill": probe_ok,
            "wrong_version": wrong_version,
            "successor_complete": bool(res.get("complete")),
            "successor_fit_dispatches": res.get("fit_dispatches"),
            "unchanged_bitwise": bitwise,
        }
        errs = []
        if child.returncode == 0:
            errs.append("refit child survived its armed delta_publish "
                        "exit fault")
        if res.get("fit_dispatches"):
            errs.append("successor re-dispatched fit waves instead of "
                        "resuming from landed flushes")
        if not probe_ok or wrong_version:
            errs.append("pool served something other than the last "
                        "complete version after the kill")
        if errs:
            inv_refit["errors"] = errs
        stage = {
            "wall_s": round(time.time() - t0, 3),
            "delta_seq": delta_rec["seq"],
            "n_changed": res.get("n_changed"),
            "v1": v1, "v2": v2,
            "child_rc": child.returncode,
            "successor": {k: res.get(k) for k in
                          ("fit_dispatches", "resumed", "wall_s",
                           "publish_s", "flip_s")},
        }
        return stage, {"refit_delta_publish": inv_refit}
    finally:
        if pool is not None:
            pool.stop()
        if env_plan is not None:
            os.environ[faults.ENV_VAR] = env_plan


# ---------------------------------------------------------------------------
# stage I: always-on scheduler loop under storm
# ---------------------------------------------------------------------------


def _run_sched_storm(scratch: str, storm: StormPlan,
                     mttr: Dict[str, Optional[float]],
                     deadline_s: float) -> Tuple[Dict, Dict]:
    """The loop-storm class: a CHAIN of scheduler (``python -m
    tsspark_tpu.sched``) deaths, one per stage the always-on loop
    drives — exit faults at ``sched_detect``, ``resident_flush``,
    ``delta_publish`` and ``sched_flip``, each successor resuming the
    SAME pinned ``refit_plan.json`` — then a raw SIGKILL of the
    scheduler process mid-cycle, and a final in-process successor that
    completes the backlog through the pool flip.

    Invariants: every armed kill fired exactly once and killed its
    child; the pool served ONLY the last complete version throughout
    (zero wrong-version); successors resumed landed work (the chunk
    flushes landed before a kill are never re-fit — pinned by mtime);
    the final snapshot's unchanged rows are bitwise its base's; and
    data-to-forecast freshness (delta land -> first pool-served
    request at a covering version) recovers within the recovery
    budget.

    Runs with the STORM env plan popped, like the refit stage: each
    child gets a PRIVATE single-point plan."""
    import glob as glob_mod
    import subprocess

    from tsspark_tpu import orchestrate, refit, resident, sched
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.pool import ReplicaPool
    from tsspark_tpu.serve.registry import ParamRegistry

    prof = storm.profile
    base = os.path.join(scratch, "sched")
    cfg, solver = _config(prof.max_iters)
    t0 = time.time()
    env_plan = os.environ.pop(faults.ENV_VAR, None)
    pool = None
    try:
        # ---- setup: private plane, cold fit, publish v1, pool -------
        spec = plane.DatasetSpec(
            generator="demo_weekly", n_series=prof.refit_series,
            n_timesteps=64, seed=storm.seed + 5,
            shard_rows=prof.plane_shard_rows,
        )
        dset = plane.ensure(spec, root=os.path.join(base, "plane"))
        ids = plane.series_ids(spec)
        out_dir = os.path.join(base, "out")
        os.makedirs(out_dir, exist_ok=True)
        orchestrate.save_run_config(out_dir, cfg, solver)
        resident.run_resident(
            data_dir=dset, out_dir=out_dir, series=prof.refit_series,
            chunk=prof.refit_chunk, phase1_iters=0,
            no_phase1_tune=True,
        )
        registry = ParamRegistry(os.path.join(base, "registry"), cfg)
        v1 = orchestrate.publish_fit_state(
            registry, out_dir, ids, step=np.ones(prof.refit_series),
            data_stamp=plane.delta_seq(dset),
        )
        pool = ReplicaPool(os.path.join(base, "pool"), registry.root,
                           n_replicas=max(2, prof.pool_replicas),
                           heartbeat_s=0.2, breaker_reset_s=0.3,
                           spawn_timeout_s=180.0)
        pool.start()
        first = pool.forecast([str(ids[0])], 5)
        assert first.get("ok") and first.get("version") == v1, first

        delta1 = plane.land_synthetic_delta(dset, prof.refit_churn)
        sched_scratch = os.path.join(base, "sched_scratch")

        def spawn_child(point: Optional[Dict],
                        timeout: float) -> Tuple:
            """One scheduler child, optionally with a single armed exit
            fault.  Returns (proc, fired_count)."""
            env = orchestrate._child_env()
            plan_dir = None
            if point is not None:
                child_plan = faults.FaultPlan(state_dir=os.path.join(
                    base, "faults", point["point"]
                ))
                # Tagged distinctly from the class: the class's
                # span-MTTR is the SIGKILL fault/recovered pair, and
                # the chain's four armed kills must not become its
                # "first fault" (they recover via the NEXT child, not
                # the measured final successor).
                child_plan.fail(point["point"], attempts=1,
                                after=point["after"], mode="exit",
                                rc=point["rc"], tag="loop-storm-kill")
                env[faults.ENV_VAR] = child_plan.to_env()
                plan_dir = child_plan
            obs.inject_env(env)
            cmd = [sys.executable, "-m", "tsspark_tpu.sched",
                   "--data", dset, "--registry", registry.root,
                   "--scratch", sched_scratch,
                   "--chunk", str(prof.refit_chunk),
                   "--max-iters", str(prof.max_iters),
                   "--poll", "0.02", "--debounce", "0.02",
                   "--until-stamp", str(plane.delta_seq(dset)),
                   "--duration", "90", "--no-activate"]
            proc = subprocess.Popen(cmd, env=env, stdout=sys.stderr)
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            fired = 0
            if plan_dir is not None:
                fired = len(inv.fault_firing_times(
                    plan_dir.state_dir,
                    {plan_dir.rules[0]["id"]: "loop-storm"},
                    plan_dir.rules,
                ).get("loop-storm", []))
            return proc, fired

        points = [i for i in storm.injections
                  if i.cls == "loop-storm" and i.point != "sched_proc"]
        chain: List[Dict] = []
        landed_mtimes: Dict[str, float] = {}
        served_v1_throughout = True
        for inj in points:
            proc, fired = spawn_child(
                {"point": inj.point, "after": inj.after,
                 "rc": inj.rc},
                timeout=min(120.0, deadline_s),
            )
            probe = pool.forecast([str(ids[0])], 5)
            ok_v1 = bool(probe.get("ok")
                         and probe.get("version") == v1)
            served_v1_throughout &= ok_v1
            rec = {"point": inj.point, "rc": proc.returncode,
                   "rc_armed": inj.rc, "fired": fired,
                   "served_v1": ok_v1,
                   "active": registry.active_version()}
            plan_rec = refit.read_refit_plan(sched_scratch)
            rec["plan_pinned"] = bool(plan_rec is not None
                                      and not plan_rec.get("complete"))
            if inj.point == "resident_flush" and plan_rec is not None:
                _c, _d, chain_out = refit.cycle_paths(sched_scratch,
                                                      plan_rec)
                for p in sorted(glob_mod.glob(
                        os.path.join(chain_out, "chunk_*.npz"))):
                    landed_mtimes[p] = os.path.getmtime(p)
                rec["landed_chunks"] = len(landed_mtimes)
            chain.append(rec)
        # Landed flushes survive the chain untouched: later successors
        # resumed them rather than re-fitting (mtime-stable).
        resumed_landed = all(
            os.path.exists(p) and os.path.getmtime(p) == m
            for p, m in landed_mtimes.items()
        )

        # ---- the raw SIGKILL: mid-cycle on a fresh delta ------------
        delta2 = plane.land_synthetic_delta(dset, prof.refit_churn)
        env = orchestrate._child_env()
        obs.inject_env(env)
        proc = subprocess.Popen(
            [sys.executable, "-m", "tsspark_tpu.sched",
             "--data", dset, "--registry", registry.root,
             "--scratch", sched_scratch,
             "--chunk", str(prof.refit_chunk),
             "--max-iters", str(prof.max_iters),
             "--poll", "0.02", "--debounce", "0.02",
             "--duration", "120", "--no-activate"],
            env=env, stdout=sys.stderr,
        )
        # Kill once the delta-2 cycle is pinned (mid-cycle, not idle).
        kill_deadline = time.time() + 90.0
        while time.time() < kill_deadline:
            plan_rec = refit.read_refit_plan(sched_scratch)
            if (plan_rec is not None
                    and plan_rec.get("plan_stamp") == delta2["seq"]):
                break
            if proc.poll() is not None:
                break
            time.sleep(0.02)
        t_fault = time.time()
        obs.event("fault", tag="loop-storm", mode="direct",
                  pid=proc.pid)
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
        proc.wait()

        # ---- final successor: in-process, pool-flipped --------------
        def pool_probe(version):
            resp = pool.forecast([str(ids[1])], 5)
            return resp.get("version") if resp.get("ok") else None

        successor = sched.RefitScheduler(
            dset, registry, sched_scratch,
            chunk=prof.refit_chunk, solver_config=solver,
            warm_start=True, pool=pool,
            hot_series=[str(s) for s in ids[:8]], horizons=(5, 7),
            poll_s=0.02, debounce_s=0.02,
            freshness_probe=pool_probe,
        )
        summary = successor.run(until_stamp=delta2["seq"],
                                duration_s=min(180.0, deadline_s))
        v_final = summary.get("head_version")
        recovered = None
        if v_final is not None and summary["pending_deltas"] == 0:
            recovered = time.time() - t_fault
            obs.event("recovered", tag="loop-storm")
        mttr["loop-storm"] = recovered

        # ---- invariants ---------------------------------------------
        if v_final is not None:
            info = registry.delta_info(int(v_final)) or {}
            base_v = info.get("base_version")
            if base_v is not None:
                bitwise = inv.refit_unchanged_bitwise(
                    registry.version_dir(int(base_v)),
                    registry.version_dir(int(v_final)),
                    info.get("changed_rows") or (),
                )
            else:
                bitwise = {"ok": False,
                           "errors": ["final version is not a delta "
                                      "publish"]}
        else:
            bitwise = {"ok": False,
                       "errors": ["successor published no version"]}
        fresh = summary["freshness"]
        fresh_ok = (fresh["n"] >= 2 and fresh["max_s"] is not None
                    and fresh["max_s"] <= prof.recovery_budget_s)
        kills_ok = all(
            r["fired"] == 1 and r["rc"] == r["rc_armed"]
            for r in chain
        )
        wrong_version = pool.wrong_version
        inv_sched = {
            "ok": (kills_ok and served_v1_throughout
                   and resumed_landed and wrong_version == 0
                   and bool(summary.get("ok"))
                   and recovered is not None and fresh_ok
                   and bitwise["ok"]),
            "kill_chain": chain,
            "resumed_landed_chunks": resumed_landed,
            "served_v1_throughout": served_v1_throughout,
            "wrong_version": wrong_version,
            "successor_ok": bool(summary.get("ok")),
            "freshness": fresh,
            "freshness_within_budget": fresh_ok,
            "unchanged_bitwise": bitwise,
        }
        errs = []
        if not kills_ok:
            errs.append("a scheduler kill never fired (or the child "
                        "survived it)")
        if not served_v1_throughout or wrong_version:
            errs.append("pool served something other than the last "
                        "complete version during the kill chain")
        if not resumed_landed:
            errs.append("a successor re-fit chunk flushes that were "
                        "already landed (resume broke)")
        if not fresh_ok:
            errs.append("freshness did not recover within the "
                        "recovery budget")
        if errs:
            inv_sched["errors"] = errs
        stage = {
            "wall_s": round(time.time() - t0, 3),
            "v1": v1, "v_final": v_final,
            "delta_seqs": [delta1["seq"], delta2["seq"]],
            "kill_chain": [
                {k: r[k] for k in ("point", "rc", "fired")}
                for r in chain
            ],
            "successor": {
                k: summary.get(k)
                for k in ("cycles", "resumed_cycles", "failures",
                          "wall_s", "cycle_overhead_frac")
            },
            "freshness": fresh,
        }
        return stage, {"sched_loop_storm": inv_sched}
    finally:
        if pool is not None:
            pool.stop()
        if env_plan is not None:
            os.environ[faults.ENV_VAR] = env_plan


# ---------------------------------------------------------------------------
# stage J: storage fault domain (the durable-I/O layer) under storm
# ---------------------------------------------------------------------------


def _run_storage_storm(scratch: str, storm: StormPlan, state, ids,
                       mttr: Dict[str, Optional[float]]
                       ) -> Tuple[Dict, Dict]:
    """The five storage classes against a PRIVATE registry + data
    plane: every durable write in the stage routes through
    ``tsspark_tpu.io``, so the armed ``io_write``/``io_fsync`` rules and
    the environment-armed ``DiskBudget`` are the only faults — the
    global storm env plan is popped for the stage's duration.

    Cross-class invariants (docs/RESILIENCE.md "Storage fault domain"):
    no torn read is ever served (every ``registry.load`` returns a
    CRC-complete version), the post-fault republish is bitwise the
    fault-free publish, and the degradation ladder both descends under
    pressure and releases on relief."""
    import glob as _glob
    import subprocess
    import warnings as _warnings

    from tsspark_tpu import orchestrate
    from tsspark_tpu.data import plane
    from tsspark_tpu.io import (
        BackpressureError,
        DiskFullError,
        DiskIOError,
        active_ladder,
        current_state,
        stale_serving,
    )
    from tsspark_tpu.io import budget as iobudget
    from tsspark_tpu.serve import snapplane
    from tsspark_tpu.serve.registry import ParamRegistry

    base = os.path.join(scratch, "storage")
    os.makedirs(base, exist_ok=True)
    t0 = time.time()
    env_plan = os.environ.pop(faults.ENV_VAR, None)
    old_budget = {k: os.environ.pop(k, None)
                  for k in (iobudget.ENV_BUDGET_BYTES,
                            iobudget.ENV_BUDGET_ROOT)}
    invariants: Dict[str, Dict] = {}
    step = np.ones(len(ids))
    try:
        cfg, _solver = _config(storm.profile.max_iters)
        registry = ParamRegistry(os.path.join(base, "registry"), cfg)
        v1 = registry.publish(state, ids, step=step)
        ref_snap = registry.load()

        # ---- enospc-mid-publish: ENOSPC on a snapshot column write
        # ---- kills the publish mid-plane; the manifest never moves --
        inj_a = storm.direct("enospc-mid-publish")
        plan_a = faults.FaultPlan(
            state_dir=os.path.join(base, "faults_enospc"))
        plan_a.fail("io_write", mode="enospc", after=inj_a.after,
                    attempts=1, path="snapcol_",
                    tag="enospc-mid-publish")
        plan_a.install()
        t_fault = time.time()
        err_a: Optional[BaseException] = None
        try:
            registry.publish(state, ids, step=step,
                             snapshot_format="mmap")
        except OSError as e:
            err_a = e
        os.environ.pop(faults.ENV_VAR, None)
        obs.event("fault", tag="enospc-mid-publish", mode="direct")
        active_mid = registry.active_version()
        mid_snap = registry.load()
        v_retry = registry.publish(state, ids, step=step,
                                   snapshot_format="mmap")
        retry_snap = registry.load()
        mttr["enospc-mid-publish"] = time.time() - t_fault
        obs.event("recovered", tag="enospc-mid-publish")
        bitwise_a = inv.states_bitwise_equal(retry_snap.state,
                                             ref_snap.state)
        invariants["storage_enospc_publish"] = {
            "ok": (isinstance(err_a, DiskFullError)
                   and active_mid == v1 and mid_snap.version == v1
                   and retry_snap.version == v_retry
                   and bitwise_a["ok"]),
            "error": type(err_a).__name__ if err_a else None,
            "active_preserved": active_mid == v1,
            "served_mid_fault": mid_snap.version,
            "retry_version": v_retry,
            "retry_bitwise_vs_reference": bitwise_a,
        }

        # ---- eio-on-flip: the manifest rename that activates a
        # ---- version raises EIO; the flip fails CLEAN ---------------
        v_next = registry.publish(state, ids, step=step,
                                  activate=False)
        plan_b = faults.FaultPlan(
            state_dir=os.path.join(base, "faults_eio"))
        plan_b.fail("io_write", mode="eio", path="manifest.json",
                    tag="eio-on-flip")
        plan_b.install()
        t_fault = time.time()
        err_b: Optional[BaseException] = None
        try:
            registry.activate(v_next)
        except OSError as e:
            err_b = e
        os.environ.pop(faults.ENV_VAR, None)
        obs.event("fault", tag="eio-on-flip", mode="direct")
        active_after_eio = registry.active_version()
        registry.activate(v_next)  # fault exhausted: retry flips
        mttr["eio-on-flip"] = time.time() - t_fault
        obs.event("recovered", tag="eio-on-flip")
        invariants["storage_eio_flip"] = {
            "ok": (isinstance(err_b, DiskIOError)
                   and active_after_eio == v_retry
                   and registry.active_version() == v_next),
            "error": type(err_b).__name__ if err_b else None,
            "active_after_fault": active_after_eio,
            "active_after_retry": registry.active_version(),
        }

        # ---- short-write-torn-column: a silently truncated column
        # ---- publishes "successfully"; only the CRC sentinel and the
        # ---- fallback chain stand between it and a served forecast --
        inj_c = storm.direct("short-write-torn-column")
        frac = 0.3 + ((inj_c.series or 0) % 101) / 250.0  # [0.3, 0.7]
        plan_c = faults.FaultPlan(
            state_dir=os.path.join(base, "faults_shortw"))
        plan_c.fail("io_write", mode="shortwrite", path="snapcol_theta",
                    fraction=round(frac, 3),
                    tag="short-write-torn-column")
        plan_c.install()
        t_fault = time.time()
        v_torn = registry.publish(state, ids, step=step,
                                  snapshot_format="mmap")
        os.environ.pop(faults.ENV_VAR, None)
        obs.event("fault", tag="short-write-torn-column",
                  mode="direct", version=v_torn)
        torn_rejected = not snapplane.verify_plane(
            registry.version_dir(v_torn))
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore", RuntimeWarning)
            snap_c = registry.load()
        mttr["short-write-torn-column"] = time.time() - t_fault
        obs.event("recovered", tag="short-write-torn-column")
        invariants["storage_short_write"] = {
            "ok": (torn_rejected and snap_c.version == v_next
                   and snap_c.fallback_from == v_torn),
            "torn_version": v_torn,
            "sentinel_rejected": torn_rejected,
            "served_version": snap_c.version,
            "fallback_from": snap_c.fallback_from,
        }
        registry.activate(v_next)  # restore a good active pointer

        # ---- lost-fsync-then-kill: an activation flip lands only in
        # ---- the page cache, the process dies, the rename dies with
        # ---- it — the survivor must observe the PRE-flip truth ------
        inj_d = storm.direct("lost-fsync-then-kill")
        v_lost = registry.publish(state, ids, step=step,
                                  activate=False)
        marker = os.path.join(base, "killmarker.json")
        plan_d = faults.FaultPlan(
            state_dir=os.path.join(base, "faults_lost"))
        plan_d.fail("io_fsync", mode="lost_fsync",
                    path="manifest.json", tag="lost-fsync-then-kill")
        plan_d.fail("io_write", mode="exit", rc=inj_d.rc,
                    path="killmarker", tag="lost-fsync-then-kill")
        env = orchestrate._child_env()
        env[faults.ENV_VAR] = plan_d.to_env()
        obs.inject_env(env)
        code = (
            "from tsspark_tpu.io import atomic_write_text\n"
            "from tsspark_tpu.serve.registry import ParamRegistry\n"
            f"r = ParamRegistry.open({registry.root!r})\n"
            f"r.activate({int(v_lost)})\n"
            # The flip 'succeeded' in-process; the armed kill below
            # replays the lost fsync (rolls the manifest back) and dies.
            f"atomic_write_text({marker!r}, 'never lands')\n"
        )
        child = subprocess.run([sys.executable, "-c", code], env=env,
                               stdout=sys.stderr, timeout=120)
        # MTTR clock starts when the kill is OBSERVED (child exit), as
        # at every other kill class — not at child launch, which would
        # bill interpreter startup to the recovery path.
        t_fault = time.time()
        obs.event("fault", tag="lost-fsync-then-kill", mode="direct",
                  rc=child.returncode)
        active_after_kill = registry.active_version()
        survivor_snap = registry.load()
        replayed = _glob.glob(os.path.join(
            plan_d.state_dir, "lostfsync", "rec.*.json.done"))
        registry.activate(v_lost)  # the successor re-flips cleanly
        mttr["lost-fsync-then-kill"] = time.time() - t_fault
        obs.event("recovered", tag="lost-fsync-then-kill")
        invariants["storage_lost_fsync"] = {
            "ok": (child.returncode == inj_d.rc
                   and active_after_kill == v_next
                   and survivor_snap.version == v_next
                   and not os.path.exists(marker)
                   and len(replayed) == 1
                   and registry.active_version() == v_lost),
            "child_rc": child.returncode,
            "active_after_kill": active_after_kill,
            "served_after_kill": survivor_snap.version,
            "rollback_replayed": len(replayed),
            "marker_landed": os.path.exists(marker),
            "active_after_resume": registry.active_version(),
        }

        # ---- disk-pressure-brownout: a byte budget strangles the
        # ---- root; the ladder must descend in order and release -----
        spec = plane.DatasetSpec(
            generator="demo_weekly", n_series=16, n_timesteps=48,
            seed=storm.seed + 5, shard_rows=8,
        )
        dset = plane.ensure(spec, root=os.path.join(base, "plane"))
        rec0 = plane.land_synthetic_delta(dset, 0.25)
        used = iobudget.DiskBudget(base).used_bytes()
        os.environ[iobudget.ENV_BUDGET_ROOT] = base
        os.environ[iobudget.ENV_BUDGET_BYTES] = str(used + 1024)
        t_fault = time.time()
        obs.event("fault", tag="disk-pressure-brownout", mode="direct")
        lad = active_ladder(dset)
        state_tight = current_state(dset)
        shed = lad is not None and not lad.allows("speculate")
        stale = stale_serving(registry.root)
        bp: Optional[BaseException] = None
        try:
            plane.land_synthetic_delta(dset, 0.25)
        except BackpressureError as e:
            bp = e
        full_err: Optional[BaseException] = None
        try:
            registry.publish(state, ids, step=step)
        except DiskFullError as e:
            full_err = e
        under_pressure = registry.load()
        # Relief: a 50x budget — the ladder must release (hysteresis
        # permitting; the REAL filesystem's free fraction still caps
        # headroom) far enough to resume delta ingestion.
        os.environ[iobudget.ENV_BUDGET_BYTES] = str(used * 50)
        state_relief = current_state(dset)
        rec2 = plane.land_synthetic_delta(dset, 0.25)
        mttr["disk-pressure-brownout"] = time.time() - t_fault
        obs.event("recovered", tag="disk-pressure-brownout")
        unstale = not stale_serving(registry.root)
        invariants["storage_brownout"] = {
            "ok": (state_tight == "stale_serve" and shed and stale
                   and isinstance(bp, BackpressureError)
                   and isinstance(full_err, DiskFullError)
                   and under_pressure.version == v_lost
                   and rec2["seq"] > rec0["seq"] and unstale),
            "ladder_under_pressure": state_tight,
            "speculation_shed": shed,
            "stale_serving_flagged": stale,
            "ingest_backpressure": type(bp).__name__ if bp else None,
            "publish_refused": (type(full_err).__name__
                               if full_err else None),
            "served_under_pressure": under_pressure.version,
            "ladder_after_relief": state_relief,
            "ingest_resumed": rec2["seq"] > rec0["seq"],
            "unstale_after_relief": unstale,
        }

        stage = {
            "wall_s": round(time.time() - t0, 3),
            "v1": v1, "enospc_retry": v_retry, "eio_flip": v_next,
            "torn": v_torn, "lost_fsync_flip": v_lost,
            "brownout": {
                "used_bytes": used,
                "ladder": [state_tight, state_relief],
                "delta_seqs": [rec0["seq"], rec2["seq"]],
            },
        }
        return stage, invariants
    finally:
        os.environ.pop(faults.ENV_VAR, None)
        for k, v in old_budget.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if env_plan is not None:
            os.environ[faults.ENV_VAR] = env_plan


# ---------------------------------------------------------------------------
# stage K: torn forecast plane (serve/fplane.py)
# ---------------------------------------------------------------------------


def _run_fplane_storm(scratch: str, storm: StormPlan, state, ids,
                      mttr: Dict[str, Optional[float]],
                      deadline_s: float) -> Tuple[Dict, Dict]:
    """The torn-forecast-plane class: a publisher child is killed MID
    forecast-plane publish (armed ``fplane_publish`` exit fault between
    column writes — spec landed, CRC sentinel never did).  Invariants
    (docs/SERVING.md "Forecast plane"): the sentinel REJECTS the torn
    plane, the engine keeps answering through its compute path with
    forecasts bitwise the direct dispatch math's (never a wrong number,
    never an outage), the retried publish verifies clean, and the
    plane-served rows afterwards are bitwise the fallback's answers.

    Runs with the storm env plan popped: the stage's only fault is the
    child's PRIVATE plan — an exit fault firing in-process would kill
    the harness itself."""
    import subprocess

    from tsspark_tpu import orchestrate
    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.serve import fplane
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import PredictionEngine
    from tsspark_tpu.serve.registry import ParamRegistry

    base = os.path.join(scratch, "fplane")
    os.makedirs(base, exist_ok=True)
    t0 = time.time()
    env_plan = os.environ.pop(faults.ENV_VAR, None)
    try:
        cfg, solver = _config(storm.profile.max_iters)
        registry = ParamRegistry(os.path.join(base, "registry"), cfg)
        v1 = registry.publish(state, ids, step=np.ones(len(ids)))
        vdir = registry.version_dir(v1)

        # ---- the kill: a publisher child with fplane_publish armed --
        inj_fp = storm.direct("torn-forecast-plane")
        child_plan = faults.FaultPlan(
            state_dir=os.path.join(base, "faults"))
        child_plan.fail("fplane_publish", attempts=1,
                        after=inj_fp.after, mode="exit", rc=inj_fp.rc,
                        tag="torn-forecast-plane")
        env = orchestrate._child_env()
        env[faults.ENV_VAR] = child_plan.to_env()
        obs.inject_env(env)
        child = subprocess.run(
            [sys.executable, "-c",
             "import sys\n"
             "from tsspark_tpu.serve import fplane\n"
             "from tsspark_tpu.serve.registry import ParamRegistry\n"
             "reg = ParamRegistry.open(sys.argv[1])\n"
             "fplane.maybe_publish(reg, int(sys.argv[2]))\n",
             registry.root, str(v1)],
            env=env, stdout=sys.stderr, timeout=deadline_s,
        )
        t_fault = time.time()
        obs.event("fault", tag="torn-forecast-plane", mode="direct",
                  rc=child.returncode)
        fired = inv.fault_firing_times(
            child_plan.state_dir,
            {child_plan.rules[0]["id"]: "torn-forecast-plane"},
            child_plan.rules,
        ).get("torn-forecast-plane", [])

        # ---- mid-tear: sentinel verdict + compute-path fallback -----
        torn_rejected = not fplane.verify_plane(vdir)
        engine = PredictionEngine(registry, cache=ForecastCache(0))
        engine.refresh()
        sids = [str(s) for s in ids[:4]]
        horizons = fplane.DEFAULT_HOT_HORIZONS
        fallback = {h: engine.forecast(sids, int(h), num_samples=0,
                                       seed=0)
                    for h in horizons}
        stats_mid = engine.stats.snapshot()
        outage_free = all(r.version == v1 for r in fallback.values())
        no_plane_hits = not stats_mid.get("plane_hits")

        # Wrong-number check: the fallback answers against the direct
        # dispatch math over the same rows (the serve stage's oracle).
        backend = get_backend("tpu", cfg, solver)
        snap = registry.load()
        parity = True
        for h, res in fallback.items():
            grid, ref = _direct_forecast(backend, snap, sids, int(h))
            parity = (parity and np.array_equal(res.ds, grid)
                      and all(np.array_equal(res.values[k], ref[k])
                              for k in res.values))

        # ---- retry: the in-process successor republishes ------------
        retry = fplane.maybe_publish(registry, v1, backend,
                                     force=True)
        retry_ok = bool(retry and retry.get("status") == "published")
        plane_good = fplane.verify_plane(vdir)
        attached = engine.attach_plane(v1)
        if plane_good:
            mttr["torn-forecast-plane"] = time.time() - t_fault
            obs.event("recovered", tag="torn-forecast-plane")
        served = {h: engine.forecast(sids, int(h), num_samples=0,
                                     seed=0)
                  for h in horizons}
        stats_after = engine.stats.snapshot()
        plane_served = (stats_after.get("plane_hits") or 0) > 0
        bitwise = all(
            np.array_equal(served[h].ds, fallback[h].ds)
            and all(np.array_equal(served[h].values[k],
                                   fallback[h].values[k])
                    for k in fallback[h].values)
            for h in horizons
        )

        inv_fp = {
            "ok": (child.returncode != 0 and len(fired) == 1
                   and torn_rejected and outage_free and no_plane_hits
                   and parity and retry_ok and plane_good
                   and attached and plane_served and bitwise),
            "child_rc": child.returncode,
            "fault_fired": len(fired),
            "sentinel_rejected_tear": torn_rejected,
            "fallback_served_v1": outage_free,
            "fallback_plane_hits": stats_mid.get("plane_hits"),
            "fallback_vs_direct_bitwise": parity,
            "retry_status": None if retry is None
            else retry.get("status"),
            "retry_plane_verified": plane_good,
            "plane_served_after_retry": plane_served,
            "plane_vs_compute_bitwise": bitwise,
        }
        errs = []
        if child.returncode == 0:
            errs.append("publisher child survived its armed "
                        "fplane_publish exit fault")
        if not torn_rejected:
            errs.append("CRC sentinel accepted a torn forecast plane")
        if not (outage_free and parity):
            errs.append("compute fallback served a wrong number or an "
                        "outage behind the torn plane")
        if not bitwise:
            errs.append("retried plane serves different bytes than "
                        "the compute path")
        if errs:
            inv_fp["errors"] = errs
        stage = {
            "wall_s": round(time.time() - t0, 3),
            "v1": v1,
            "child_rc": child.returncode,
            "kill_after_columns": inj_fp.after,
            "retry": retry,
        }
        return stage, {"fplane_torn_publish": inv_fp}
    finally:
        if env_plan is not None:
            os.environ[faults.ENV_VAR] = env_plan


# ---------------------------------------------------------------------------
# stage L: torn quantile plane (uncertainty/qplane.py)
# ---------------------------------------------------------------------------


def _run_qplane_storm(scratch: str, storm: StormPlan, state, ids,
                      mttr: Dict[str, Optional[float]],
                      deadline_s: float) -> Tuple[Dict, Dict]:
    """The torn-quantile-plane class: a publisher child is killed MID
    quantile-plane publish (armed ``qplane_publish`` exit fault between
    column writes — spec landed, CRC sentinel never did).  Invariants
    (docs/UNCERTAINTY.md): the sentinel REJECTS the torn plane, the
    engine keeps answering interval reads through the row-local compute
    fallback with bands bitwise the direct ``compute_rows`` math's
    (never a wrong band, never an outage), the retried publish verifies
    clean, and the plane-served rows afterwards are bitwise the
    fallback's answers.

    Runs with the storm env plan popped: the stage's only fault is the
    child's PRIVATE plan — an exit fault firing in-process would kill
    the harness itself."""
    import subprocess

    from tsspark_tpu import orchestrate
    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.parallel.sharding import next_pow2
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import PredictionEngine
    from tsspark_tpu.serve.fplane import DEFAULT_HOT_HORIZONS
    from tsspark_tpu.serve.registry import ParamRegistry
    from tsspark_tpu.uncertainty import qplane

    base = os.path.join(scratch, "qplane")
    os.makedirs(base, exist_ok=True)
    t0 = time.time()
    env_plan = os.environ.pop(faults.ENV_VAR, None)
    try:
        cfg, solver = _config(storm.profile.max_iters)
        registry = ParamRegistry(os.path.join(base, "registry"), cfg)
        v1 = registry.publish(state, ids, step=np.ones(len(ids)))
        vdir = registry.version_dir(v1)

        # ---- the kill: a publisher child with qplane_publish armed --
        inj_qp = storm.direct("torn-quantile-plane")
        child_plan = faults.FaultPlan(
            state_dir=os.path.join(base, "faults"))
        child_plan.fail("qplane_publish", attempts=1,
                        after=inj_qp.after, mode="exit", rc=inj_qp.rc,
                        tag="torn-quantile-plane")
        env = orchestrate._child_env()
        env[faults.ENV_VAR] = child_plan.to_env()
        obs.inject_env(env)
        child = subprocess.run(
            [sys.executable, "-c",
             "import sys\n"
             "from tsspark_tpu.uncertainty import qplane\n"
             "from tsspark_tpu.serve.registry import ParamRegistry\n"
             "reg = ParamRegistry.open(sys.argv[1])\n"
             "qplane.maybe_publish(reg, int(sys.argv[2]))\n",
             registry.root, str(v1)],
            env=env, stdout=sys.stderr, timeout=deadline_s,
        )
        t_fault = time.time()
        obs.event("fault", tag="torn-quantile-plane", mode="direct",
                  rc=child.returncode)
        fired = inv.fault_firing_times(
            child_plan.state_dir,
            {child_plan.rules[0]["id"]: "torn-quantile-plane"},
            child_plan.rules,
        ).get("torn-quantile-plane", [])

        # ---- mid-tear: sentinel verdict + compute-path fallback -----
        torn_rejected = not qplane.verify_qplane(vdir)
        engine = PredictionEngine(registry, cache=ForecastCache(0))
        engine.refresh()
        sids = [str(s) for s in ids[:4]]
        horizons = DEFAULT_HOT_HORIZONS
        fallback = {h: engine.quantiles(sids, int(h))
                    for h in horizons}
        stats_mid = engine.stats.snapshot()
        outage_free = all(r.version == v1 for r in fallback.values())
        no_plane_hits = not stats_mid.get("qplane_hits")

        # Wrong-band check: the fallback answers against the row-local
        # sampler run directly over the same snapshot rows (the
        # interval tier's oracle — compute_rows IS the parity
        # contract, so an independent call must land the same bytes).
        backend = get_backend("tpu", cfg, solver)
        snap = registry.load()
        idx, _ = snap.rows(sids)
        idx = np.asarray(idx, np.int64)
        parity = True
        for h, res in fallback.items():
            hb = max(engine.horizon_floor, next_pow2(int(h)))
            ref = qplane.compute_rows(snap, cfg, backend, idx, hb)
            parity = parity and all(
                np.array_equal(res.values[f"q{pm:03d}"],
                               ref[pm][:, :int(h)])
                for pm in ref
            )

        # ---- retry: the in-process successor republishes ------------
        retry = qplane.maybe_publish(registry, v1, backend, force=True)
        retry_ok = bool(retry and retry.get("status") == "published")
        plane_good = qplane.verify_qplane(vdir)
        attached = engine.attach_qplane(v1)
        if plane_good:
            mttr["torn-quantile-plane"] = time.time() - t_fault
            obs.event("recovered", tag="torn-quantile-plane")
        served = {h: engine.quantiles(sids, int(h)) for h in horizons}
        stats_after = engine.stats.snapshot()
        plane_served = (stats_after.get("qplane_hits") or 0) > 0
        bitwise = all(
            np.array_equal(served[h].ds, fallback[h].ds)
            and all(np.array_equal(served[h].values[k],
                                   fallback[h].values[k])
                    for k in fallback[h].values)
            for h in horizons
        )

        inv_qp = {
            "ok": (child.returncode != 0 and len(fired) == 1
                   and torn_rejected and outage_free and no_plane_hits
                   and parity and retry_ok and plane_good
                   and attached and plane_served and bitwise),
            "child_rc": child.returncode,
            "fault_fired": len(fired),
            "sentinel_rejected_tear": torn_rejected,
            "fallback_served_v1": outage_free,
            "fallback_qplane_hits": stats_mid.get("qplane_hits"),
            "fallback_vs_compute_bitwise": parity,
            "retry_status": None if retry is None
            else retry.get("status"),
            "retry_plane_verified": plane_good,
            "plane_served_after_retry": plane_served,
            "plane_vs_compute_bitwise": bitwise,
        }
        errs = []
        if child.returncode == 0:
            errs.append("publisher child survived its armed "
                        "qplane_publish exit fault")
        if not torn_rejected:
            errs.append("CRC sentinel accepted a torn quantile plane")
        if not (outage_free and parity):
            errs.append("compute fallback served a wrong band or an "
                        "outage behind the torn quantile plane")
        if not bitwise:
            errs.append("retried quantile plane serves different "
                        "bytes than the compute path")
        if errs:
            inv_qp["errors"] = errs
        stage = {
            "wall_s": round(time.time() - t0, 3),
            "v1": v1,
            "child_rc": child.returncode,
            "kill_after_columns": inj_qp.after,
            "retry": retry,
        }
        return stage, {"qplane_torn_publish": inv_qp}
    finally:
        if env_plan is not None:
            os.environ[faults.ENV_VAR] = env_plan


def _run_alerts_storm(scratch: str, storm: StormPlan, state,
                      mttr: Dict[str, Optional[float]],
                      deadline_s: float) -> Tuple[Dict, Dict]:
    """The alert-stream fault domain (tsspark_tpu.alerts): three
    classes against a live exactly-once pipeline.

    * alert-scorer-kill — the scorer CHILD (``python -m
      tsspark_tpu.alerts --poll-once``) dies twice: once at the armed
      ``alert_publish`` exit fault (before the record, between record
      and CRC sentinel, or right after it — the draw picks), once at
      ``alert_deliver`` mid sink emit with alerts already acked.  The
      successor must re-score any uncertified delta BITWISE (the
      orphan record's bytes are the oracle) and redeliver past the
      watermark with the sink's key set deduping every repeat.
    * alert-sink-brownout — the sink raises for a window: the breaker
      opens, the watermark HOLDS, and the drain after relief is clean.
    * torn-alert-record — a certified record's bytes are flipped under
      its sentinel: the CRC check rejects it and the re-score
      converges bitwise to the pre-tear bytes.

    All of it collapses into ``alerts_exactly_once``: every alert key
    the certified records expect is in the sink exactly once.

    Runs with the storm env plan popped: the children get PRIVATE
    plans — an exit fault firing in-process would kill the harness."""
    import subprocess

    from tsspark_tpu import orchestrate
    from tsspark_tpu.alerts.sink import FlakySink, JsonlSink
    from tsspark_tpu.alerts.stream import AlertStream
    from tsspark_tpu.data import plane
    from tsspark_tpu.serve.cache import ForecastCache
    from tsspark_tpu.serve.engine import PredictionEngine
    from tsspark_tpu.serve.registry import ParamRegistry

    base = os.path.join(scratch, "alerts")
    os.makedirs(base, exist_ok=True)
    t0 = time.time()
    prof = storm.profile
    env_plan = os.environ.pop(faults.ENV_VAR, None)
    try:
        cfg, _solver = _config(prof.max_iters)
        spec = plane.DatasetSpec(generator="demo_weekly",
                                 n_series=prof.series,
                                 n_timesteps=prof.days, seed=2)
        dset = plane.ensure(spec, root=os.path.join(base, "plane"))
        pids = plane.series_ids(spec)
        registry = ParamRegistry(os.path.join(base, "registry"), cfg)
        v1 = registry.publish(state, pids,
                              step=np.ones(len(pids)))
        log_dir = os.path.join(base, "log")
        sink_path = os.path.join(base, "sink.jsonl")
        # z tiny so every churned row FIRES (the storm needs alerts in
        # flight, not a quiet fleet); overdue_k huge so data-liveness
        # stays silent — the exactly-once ledger below is then exactly
        # the certified records' alert keys.
        z_fire, k_quiet = 0.05, 1e9
        rng = np.random.default_rng([29, storm.seed])
        churn_rows = max(4, prof.series // 3)

        def _land():
            rows = np.sort(rng.choice(prof.series, size=churn_rows,
                                      replace=False)).astype(np.int64)
            plane.land_synthetic_delta(dset, 0.25, rows=rows)

        def _scorer_child(point: str, after: int, rc: int):
            plan = faults.FaultPlan(
                state_dir=os.path.join(base, f"faults_{point}"))
            plan.fail(point, attempts=1, after=after, mode="exit",
                      rc=rc, tag="alert-scorer-kill")
            env = orchestrate._child_env()
            env[faults.ENV_VAR] = plan.to_env()
            obs.inject_env(env)
            return subprocess.run(
                [sys.executable, "-m", "tsspark_tpu.alerts",
                 "--data", dset, "--registry", registry.root,
                 "--alerts-dir", log_dir,
                 "--sink", f"jsonl:{sink_path}",
                 "--z", str(z_fire), "--overdue-k", str(k_quiet),
                 "--poll-once"],
                env=env, stdout=sys.stderr, timeout=deadline_s,
            )

        def _stream(sink=None, breaker=None) -> AlertStream:
            engine = PredictionEngine(registry,
                                      cache=ForecastCache(0))
            return AlertStream(
                log_dir, dset, engine,
                sink if sink is not None else JsonlSink(sink_path),
                horizon=1, z=z_fire, overdue_k=k_quiet,
                breaker=breaker,
            )

        def _rec_bytes(seq: int) -> Optional[bytes]:
            p = os.path.join(log_dir, f"alertrec_{seq:06d}.json")
            if not os.path.exists(p):
                return None
            with open(p, "rb") as fh:
                return fh.read()

        # ---- class 1a: scorer killed MID-PUBLISH --------------------
        _land()
        _land()
        inj_pub = next(i for i in storm.injections
                       if i.cls == "alert-scorer-kill"
                       and i.point == "alert_publish")
        child1 = _scorer_child("alert_publish", inj_pub.after,
                               inj_pub.rc)
        t_fault = time.time()
        obs.event("fault", tag="alert-scorer-kill", mode="direct",
                  point="alert_publish", rc=child1.returncode)
        probe = _stream()
        orphans = {
            seq: _rec_bytes(seq)
            for seq in range(1, plane.delta_seq(dset) + 1)
            if probe.record_ok(seq) is None
            and _rec_bytes(seq) is not None
        }
        res1 = probe.poll_once()
        mttr["alert-scorer-kill"] = time.time() - t_fault
        obs.event("recovered", tag="alert-scorer-kill")
        rescore_bitwise = all(_rec_bytes(s) == b
                              for s, b in orphans.items())
        pub_kill = {
            "child_rc": child1.returncode,
            "kill_after_sites": inj_pub.after,
            "orphan_records": sorted(orphans),
            "rescore_bitwise": rescore_bitwise,
            "scored": probe.scored_seq(),
            "watermark": probe.delivered_seq(),
        }

        # ---- class 1b: scorer killed MID-DELIVERY -------------------
        _land()
        inj_del = next(i for i in storm.injections
                       if i.cls == "alert-scorer-kill"
                       and i.point == "alert_deliver")
        child2 = _scorer_child("alert_deliver", inj_del.after,
                               inj_del.rc)
        t_fault = time.time()
        obs.event("fault", tag="alert-scorer-kill", mode="direct",
                  point="alert_deliver", rc=child2.returncode)
        s2 = _stream()
        res2 = s2.poll_once()
        mttr["alert-scorer-kill"] = max(mttr["alert-scorer-kill"],
                                        time.time() - t_fault)
        obs.event("recovered", tag="alert-scorer-kill")
        del_kill = {
            "child_rc": child2.returncode,
            "kill_after_emits": inj_del.after,
            "redelivered": res2["delivered"],
            "deduped": res2["deduped"],
            "watermark": s2.delivered_seq(),
        }
        inv_kill = {
            "ok": (child1.returncode == inj_pub.rc
                   and child2.returncode == inj_del.rc
                   and rescore_bitwise
                   and res2["deduped"] >= 1
                   and s2.delivered_seq() == s2.scored_seq()),
            **pub_kill,
            "deliver": del_kill,
        }
        errs = []
        if child1.returncode != inj_pub.rc or \
                child2.returncode != inj_del.rc:
            errs.append("a scorer child survived its armed exit fault")
        if not rescore_bitwise:
            errs.append("successor re-score diverged from the orphan "
                        "record's bytes")
        if res2["deduped"] < 1:
            errs.append("redelivery after the mid-delivery kill "
                        "deduped nothing — the pre-kill acks were "
                        "lost or the kill landed before any emit")
        if errs:
            inv_kill["errors"] = errs

        # ---- class 2: sink brownout ---------------------------------
        inj_bro = storm.direct("alert-sink-brownout")
        _land()
        flaky = FlakySink(JsonlSink(sink_path),
                          fail_n=inj_bro.attempts)
        breaker = CircuitBreaker(failure_threshold=3,
                                 reset_timeout_s=0.2,
                                 name="alert-sink")
        s3 = _stream(sink=flaky, breaker=breaker)
        wm_before = s3.delivered_seq()
        obs.event("fault", tag="alert-sink-brownout", mode="direct")
        t_fault = time.time()
        res3 = s3.poll_once()
        opened = s3.breaker.snapshot()["state"] == "open"
        held = s3.delivered_seq() == wm_before
        flaky.fail_n = 0          # relief
        time.sleep(0.25)          # past the breaker's reset window
        res3b = s3.poll_once()
        drained = (not res3b["stalled"]
                   and s3.delivered_seq() == s3.scored_seq())
        mttr["alert-sink-brownout"] = time.time() - t_fault
        obs.event("recovered", tag="alert-sink-brownout")
        inv_bro = {
            "ok": (res3["stalled"] and opened and held and drained),
            "fail_n": inj_bro.attempts,
            "stalled": res3["stalled"],
            "breaker_opened": opened,
            "watermark_held": held,
            "drained_after_relief": drained,
            "sink_failures": flaky.failures,
            "breaker": s3.breaker.snapshot(),
        }
        if not inv_bro["ok"]:
            inv_bro["errors"] = [
                "brownout did not stall/open/hold/drain as required"
            ]

        # ---- class 3: torn certified record -------------------------
        tseq = s3.scored_seq()
        orig = _rec_bytes(tseq)
        obs.event("fault", tag="torn-alert-record", mode="direct",
                  seq=tseq)
        t_fault = time.time()
        rp = os.path.join(log_dir, f"alertrec_{tseq:06d}.json")
        # Tear through the blessed corruption injector (the one writer
        # allowed to touch bytes non-atomically): a private
        # corrupt-mode rule at alert_record, armed for exactly one
        # call — same shape as the registry-corrupt class.
        tear = faults.FaultPlan(
            state_dir=os.path.join(base, "tear_faults")
        )
        tear.fail("alert_record", attempts=1, mode="corrupt",
                  tag="torn-alert-record")
        os.environ[faults.ENV_VAR] = tear.to_env()
        try:
            tore = faults.corrupt_file("alert_record", rp)
        finally:
            del os.environ[faults.ENV_VAR]
        s4 = _stream()
        crc_rejected = s4.record_ok(tseq) is None
        res4 = s4.poll_once()
        healed = s4.record_ok(tseq) is not None
        torn_bitwise = _rec_bytes(tseq) == orig
        mttr["torn-alert-record"] = time.time() - t_fault
        obs.event("recovered", tag="torn-alert-record")
        inv_torn = {
            "ok": (tore and crc_rejected and healed and torn_bitwise
                   and res4["deduped"] == 0),
            "torn_seq": tseq,
            "corruption_applied": tore,
            "crc_rejected_tear": crc_rejected,
            "rescored": healed,
            "rescore_bitwise": torn_bitwise,
            "spurious_redelivery": res4["delivered"],
        }
        if not inv_torn["ok"]:
            inv_torn["errors"] = [
                "torn record was accepted, re-scored differently, or "
                "redelivered duplicates"
            ]

        # ---- the one observable truth: the sink ---------------------
        fin = _stream()
        expected: List[str] = []
        for seq in range(1, fin.scored_seq() + 1):
            rec = fin.record_ok(seq)
            if rec is None:
                expected.append(f"<uncertified:{seq}>")
                continue
            expected.extend(a["key"] for a in rec["alerts"])
        inv_eo = inv.alerts_exactly_once(
            expected, JsonlSink(sink_path).alerts(),
            fin.delivered_seq(), fin.scored_seq(),
        )

        stage = {
            "wall_s": round(time.time() - t0, 3),
            "v1": v1,
            "deltas": plane.delta_seq(dset),
            "publish_kill": pub_kill,
            "deliver_kill": del_kill,
            "brownout_scored": res3["scored"],
            "torn_seq": tseq,
            "sink_alerts": inv_eo["delivered"],
        }
        return stage, {
            "alerts_scorer_kill": inv_kill,
            "alerts_sink_brownout": inv_bro,
            "alerts_torn_record": inv_torn,
            "alerts_exactly_once": inv_eo,
        }
    finally:
        if env_plan is not None:
            os.environ[faults.ENV_VAR] = env_plan


def run_storm(seed: int = 0, profile: str = "full",
              scratch: Optional[str] = None,
              keep_scratch: bool = False,
              deadline_s: float = 600.0,
              ledger_path: Optional[str] = None) -> Dict:
    """Run the composed storm end to end; returns the scorecard dict
    (see ``write_scorecard`` for the file form).

    The whole storm runs under ONE observability trace
    (tsspark_tpu.obs): stage spans wrap orchestrate/registry/streaming/
    serve, fault firings annotate the trace, and the resulting run
    ledger is joined back into the scorecard — the ``trace_joined``
    invariant requires zero orphan spans and span-derived MTTR agreeing
    with the claim-file-mtime measurement within 1 s.  ``ledger_path``
    additionally persists the ledger as a ``RUNLEDGER_*.json``."""
    from tsspark_tpu import orchestrate
    from tsspark_tpu.serve.registry import ParamRegistry

    storm = compose(seed, profile)
    prof = storm.profile
    own_scratch = scratch is None
    scratch = scratch or tempfile.mkdtemp(prefix="tsspark_chaos_")
    os.makedirs(scratch, exist_ok=True)
    prev_run = obs.start_run(os.path.join(scratch, "spans.jsonl"))
    # Fresh run, fresh counts: the end-of-storm snapshot must describe
    # THIS storm, not a prior run in the same process.
    METRICS.reset()
    cfg, solver = _config(prof.max_iters)
    ds, y = _synthetic_batch(seed, prof.series, prof.days)
    ids = [f"s{i:04d}" for i in range(prof.series)]

    plan, rule_cls = storm.build_fault_plan(
        os.path.join(scratch, "faults")
    )
    env_old = os.environ.get(faults.ENV_VAR)
    resident_old = os.environ.get("BENCH_NO_RESIDENT")
    # Pin ONE phase-2 mechanism for the faulted run and its fault-free
    # reference: a crash-resumed worker has partial device-resident
    # coverage and takes the host path, which matches the resident path
    # only to f32 noise — the bitwise invariant needs both runs on the
    # same mechanism (same pin as tests/test_resilience.py).
    os.environ["BENCH_NO_RESIDENT"] = "1"
    stages: Dict[str, Dict] = {}
    mttr: Dict[str, Optional[float]] = {}
    invariants: Dict[str, Dict] = {}
    try:
        out_dir: Optional[str] = None
        if prof.run_orchestrate:
            # ---- stage A: orchestrate under storm --------------------
            os.environ[faults.ENV_VAR] = plan.to_env()
            with obs.span("stage.orchestrate", seed=seed,
                          profile=profile):
                stages["orchestrate"] = _run_orchestrate(
                    scratch, "storm", ds, y, cfg, solver, storm,
                    deadline_s
                )
                t_end_orch = time.time()
            os.environ.pop(faults.ENV_VAR, None)
            out_dir = stages["orchestrate"]["out_dir"]

            fired = inv.fault_firing_times(
                plan.state_dir, rule_cls, plan.rules
            )
            orch_classes = {i.cls for i in storm.injections
                            if i.stage in ("orchestrate",)}
            mttr.update(inv.orchestrate_mttr(
                {c: t for c, t in fired.items() if c in orch_classes},
                out_dir, t_end_orch,
            ))

            # ---- exactly-once: coverage + bitwise vs fault-free ------
            ranges = orchestrate.completed_ranges(out_dir)
            invariants["series_exactly_once"] = \
                inv.coverage_exactly_once(ranges, prof.series)
            got_state = orchestrate.load_fit_state(out_dir, prof.series)
            with obs.span("stage.reference"):
                stages["reference"] = _run_orchestrate(
                    scratch, "reference", ds, y, cfg, solver, storm,
                    deadline_s
                )
            ref_state = orchestrate.load_fit_state(
                stages["reference"]["out_dir"], prof.series
            )
            bitwise = inv.states_bitwise_equal(got_state, ref_state)
            invariants["series_exactly_once"]["bitwise_vs_reference"] \
                = bitwise
            invariants["series_exactly_once"]["ok"] &= bitwise["ok"]
            if not stages["orchestrate"]["complete"]:
                invariants["series_exactly_once"]["ok"] = False
                invariants["series_exactly_once"].setdefault(
                    "errors", []
                ).append("orchestrate run did not complete its coverage")
        else:
            # Pool-profile fast path: one in-process fit feeds the
            # registry (the orchestrate fault classes are not armed).
            import jax.numpy as jnp

            from tsspark_tpu.backends.registry import get_backend

            with obs.span("stage.fit", series=prof.series):
                backend = get_backend("tpu", cfg, solver)
                got_state = backend.fit(ds, jnp.asarray(y))
                stages["fit"] = {"series": prof.series}

        # ---- stage B: registry publish + corrupt-active fallback -----
        os.environ[faults.ENV_VAR] = plan.to_env()
        with obs.span("stage.registry"):
            registry = ParamRegistry(os.path.join(scratch, "registry"),
                                     cfg)
            if out_dir is not None:
                v1 = orchestrate.publish_fit_state(
                    registry, out_dir, ids, step=np.ones(prof.series)
                )
            else:
                v1 = registry.publish(got_state, ids,
                                      step=np.ones(prof.series))
            # v2 is published npz-only so the legacy registry-corrupt
            # class keeps its meaning (the ARCHIVAL format is the torn
            # artifact; an intact plane would legitimately serve v2).
            v2 = registry.publish(
                got_state._replace(
                    theta=np.asarray(got_state.theta) * 1.01
                ),
                ids, step=np.ones(prof.series),
                snapshot_format="npz",
            )
            snap_path = os.path.join(
                registry.root, f"v{v2:06d}", "state.npz"
            )
            corrupted = faults.corrupt_file(REGISTRY_SNAPSHOT_POINT,
                                            snap_path)
            t_corrupt = time.time()
            import warnings as _warnings

            with _warnings.catch_warnings():
                _warnings.simplefilter("ignore", RuntimeWarning)
                fb_snap = registry.load()
            mttr["registry-corrupt"] = time.time() - t_corrupt
        invariants["registry_fallback"] = {
            "ok": (corrupted and fb_snap.version == v1
                   and fb_snap.fallback_from == v2),
            "corrupt_version": v2,
            "served_version": fb_snap.version,
            "fallback_from": fb_snap.fallback_from,
        }
        stages["registry"] = {"v1": v1, "v2_corrupt": v2,
                              "fallback_served": fb_snap.version}

        # ---- snapshot-torn-shard: tear the ACTIVE version's mmap
        # ---- plane under its CRC sentinel, mid-flip ------------------
        torn_inj = storm.direct("snapshot-torn-shard")
        if torn_inj is not None:
            from tsspark_tpu.serve import snapplane

            with obs.span("stage.snapplane"):
                # A plane-ONLY version (no archival npz): the fallback
                # chain, not the same-version npz, must absorb the tear.
                v3 = registry.publish(
                    got_state._replace(
                        theta=np.asarray(got_state.theta) * 1.03
                    ),
                    ids, step=np.ones(prof.series),
                    snapshot_format="mmap",
                )
                v3_dir = os.path.join(registry.root, f"v{v3:06d}")
                obs.event("fault", tag="snapshot-torn-shard",
                          mode="direct", version=v3)
                t_torn = time.time()
                mm = np.lib.format.open_memmap(
                    os.path.join(v3_dir, "snapcol_theta.npy"),
                    mode="r+",
                )
                row = (torn_inj.series or 0) % mm.shape[0]
                mm[row:row + 1].view(np.uint32)[...] ^= \
                    np.uint32(0x5A5A5A5A)
                mm.flush()
                del mm
                torn_rejected = not snapplane.verify_plane(v3_dir)
                with _warnings.catch_warnings():
                    _warnings.simplefilter("ignore", RuntimeWarning)
                    torn_snap = registry.load()
                mttr["snapshot-torn-shard"] = time.time() - t_torn
                obs.event("recovered", tag="snapshot-torn-shard")
            invariants["snapshot_torn_shard"] = {
                # The sentinel must reject the torn plane, the fallback
                # chain must serve the last GOOD version (v2's npz is
                # itself corrupt, so that is v1), and the served
                # parameters must never be the torn ones.
                "ok": (torn_rejected and torn_snap.version == v1
                       and torn_snap.fallback_from == v3),
                "torn_version": v3,
                "sentinel_rejected": torn_rejected,
                "served_version": torn_snap.version,
                "fallback_from": torn_snap.fallback_from,
            }
            stages["snapplane"] = {
                "v3_torn": v3, "torn_row": int(row),
                "fallback_served": torn_snap.version,
            }

        # ---- stage C: streaming under storm --------------------------
        if prof.run_streaming:
            with obs.span("stage.streaming"):
                stages["streaming"] = _run_streaming(registry, cfg,
                                                     storm, seed)
            stream_fired = inv.fault_firing_times(
                plan.state_dir, rule_cls, plan.rules
            ).get("stream-fault", [])
            if stream_fired:
                end = stages["streaming"]["end_time"]
                mttr["stream-fault"] = max(
                    (end - t for t in stream_fired), default=None
                )

        # ---- stage D: engine loadgen under storm ---------------------
        if prof.loadgen_requests:
            with obs.span("stage.serve"):
                registry.activate(v1)  # loadgen runs the full batch
                stages["serve"] = _run_serve(registry, ids, got_state,
                                             storm, mttr)

        # ---- stage E: replica pool under storm -----------------------
        if prof.pool_replicas:
            with obs.span("stage.pool", replicas=prof.pool_replicas):
                registry.activate(v1)
                stages["pool"], pool_inv = _run_pool(
                    scratch, registry, ids, got_state, storm, mttr
                )
            invariants.update(pool_inv)

        # ---- stage F: columnar data plane under storm ----------------
        if prof.plane_series:
            with obs.span("stage.data", series=prof.plane_series):
                stages["data"], plane_inv = _run_plane(scratch, storm,
                                                       mttr)
            invariants.update(plane_inv)

        # ---- stage G: mesh-resident fit program under storm ----------
        if prof.resident_series:
            with obs.span("stage.resident",
                          series=prof.resident_series):
                stages["resident"], res_inv = _run_resident_storm(
                    scratch, storm, deadline_s
                )
            invariants.update(res_inv)
            res_fired = inv.fault_firing_times(
                plan.state_dir, rule_cls, plan.rules
            ).get("resident-kill", [])
            if res_fired:
                mttr.update(inv.orchestrate_mttr(
                    {"resident-kill": res_fired},
                    os.path.join(scratch, "resident", "out"),
                    time.time(),
                ))

        # ---- stage H: delta-refit engine under storm -----------------
        if prof.refit_series:
            with obs.span("stage.refit", series=prof.refit_series):
                stages["refit"], refit_inv = _run_refit_storm(
                    scratch, storm, mttr, deadline_s
                )
            invariants.update(refit_inv)

        # ---- stage I: always-on scheduler loop under storm -----------
        if prof.sched_storm and prof.refit_series:
            with obs.span("stage.sched", series=prof.refit_series):
                stages["sched"], sched_inv = _run_sched_storm(
                    scratch, storm, mttr, deadline_s
                )
            invariants.update(sched_inv)

        # ---- stage J: storage fault domain (durable-I/O layer) -------
        if prof.storage_storm:
            with obs.span("stage.storage"):
                stages["storage"], storage_inv = _run_storage_storm(
                    scratch, storm, got_state, ids, mttr
                )
            invariants.update(storage_inv)

        # ---- stage K: torn forecast plane (serve/fplane.py) ----------
        if prof.fplane_storm:
            with obs.span("stage.fplane"):
                stages["fplane"], fp_inv = _run_fplane_storm(
                    scratch, storm, got_state, ids, mttr, deadline_s
                )
            invariants.update(fp_inv)

        # ---- stage L: torn quantile plane (uncertainty/qplane.py) ----
        if prof.qplane_storm:
            with obs.span("stage.qplane"):
                stages["qplane"], qp_inv = _run_qplane_storm(
                    scratch, storm, got_state, ids, mttr, deadline_s
                )
            invariants.update(qp_inv)

        # ---- stage M: exactly-once alert stream ----------------------
        if prof.alerts_storm:
            with obs.span("stage.alerts"):
                stages["alerts"], al_inv = _run_alerts_storm(
                    scratch, storm, got_state, mttr, deadline_s
                )
            invariants.update(al_inv)

        # ---- cross-stage invariants ----------------------------------
        if out_dir is not None:
            corrupt_injected = sum(
                1 for i in storm.injections
                if i.mode == "corrupt" and i.stage == "orchestrate"
            )
            invariants["no_torn_reads"] = inv.no_torn_reads(
                out_dir, corrupt_injected
            )
            # The registry side of no-torn-reads: the corrupt snapshot
            # was never parsed into forecasts (fallback invariant).
            invariants["no_torn_reads"]["ok"] &= \
                invariants["registry_fallback"]["ok"]

        if "serve" in stages:
            serve = stages["serve"]
            invariants["engine_direct_parity"] = {
                "ok": (not serve["parity_failures"]
                       and serve["counters"]["parity_checks"] > 0),
                "requests_checked": serve["counters"]["parity_checks"],
                "failures": serve["parity_failures"],
            }
            invariants["cache_version_consistent"] = {
                "ok": serve["cache_consistent"],
                "cache_key_versions": serve["cache_key_versions"],
                "active_version": serve["active_version"],
            }
            invariants["breaker_cycled"] = {
                "ok": serve["breaker_opened"]
                and serve["breaker"]["state"] == "closed",
                "breaker": serve["breaker"],
            }

        fired_final = inv.fault_firing_times(
            plan.state_dir, rule_cls, plan.rules
        )
        recovery_classes = set(fired_final) | {
            i.cls for i in storm.injections if i.mode == "direct"
        }
        invariants["recovery_within_budget"] = \
            inv.recovery_within_budget(
                {c: mttr.get(c) for c in sorted(recovery_classes)},
                prof.recovery_budget_s,
            )
        per_class = {}
        for c, js in storm.by_class().items():
            if js[0].mode == "direct":
                planned = fired_n = len(js)
            else:
                planned = sum(j.attempts for j in js)
                fired_n = len(fired_final.get(c, []))
            per_class[c] = {"planned": planned, "fired": fired_n}

        # ---- the run ledger: every stage joined under one trace ------
        METRICS.export(os.path.join(scratch, "metrics_harness.json"),
                       trace_id=obs.trace_id())
        # The storage fault domain's own accounting: every io.* counter
        # and gauge the storm drove (writes, classified disk errors,
        # fired storage faults, budget headroom, ladder state) — scored
        # into the report so RUNHISTORY rows carry them per storm.
        snap_m = METRICS.snapshot()
        io_metrics = {
            m["name"]: m["value"]
            for kind in ("counters", "gauges")
            for m in snap_m[kind]
            if m["name"].startswith("tsspark_io_")
        }
        ledger = obs_ledger.build_ledger(scratch)
        mttr_spans = ledger["mttr_s"]
        mttr_delta = {
            c: round(abs(mttr_spans[c] - mttr[c]), 3)
            for c in sorted(set(mttr_spans) & set(mttr))
            if mttr_spans[c] is not None and mttr[c] is not None
        }
        # Every class the mtime measurement recovered must ALSO be
        # derivable from spans — a class whose fault events never made
        # the trace would otherwise drop out of the delta comparison
        # and pass vacuously.
        mttr_missing = sorted(
            c for c, v in mttr.items()
            if v is not None and mttr_spans.get(c) is None
        )
        span_names = set(ledger["red"])
        stage_names = {"registry.publish"}
        if prof.run_orchestrate:
            stage_names.add("chunk.fit")
        if prof.run_streaming:
            stage_names.add("stream.batch")
        if prof.loadgen_requests or prof.pool_replicas:
            stage_names.add("serve.request")
        invariants["trace_joined"] = {
            # Zero orphan spans, every subsystem on the timeline, every
            # recovered fault class readable off the trace, and
            # span-derived MTTR agreeing with the claim-file-mtime
            # measurement within 1 s — the trace alone tells the same
            # recovery story the artifacts do.
            "ok": (not ledger["orphan_spans"]
                   and stage_names <= span_names
                   and not mttr_missing
                   and all(d <= 1.0 for d in mttr_delta.values())),
            "trace_id": ledger["trace_id"],
            "spans": len(ledger["spans"]),
            "processes": len(ledger["processes"]),
            "orphan_spans": ledger["orphan_spans"],
            "subsystems_missing": sorted(stage_names - span_names),
            "mttr_missing_in_spans": mttr_missing,
            "mttr_spans_s": mttr_spans,
            "mttr_delta_s": mttr_delta,
        }
        ok = all(v.get("ok") for v in invariants.values())
        import jax

        from tsspark_tpu.config import NUMERICS_REV
        from tsspark_tpu.obs.history import git_rev

        report = {
            "kind": "chaos-storm",
            "unix": round(time.time(), 3),
            "trace_id": ledger["trace_id"],
            # Cross-run identity (obs.history): the regression sentinel
            # baselines per-fault-class MTTR across matching revisions
            # and device classes.
            "numerics_rev": NUMERICS_REV,
            "git_rev": git_rev(),
            "device": str(jax.devices()[0]),
            "seed": seed,
            "profile": profile,
            "workload": {
                "series": prof.series, "days": prof.days,
                "chunk": prof.chunk, "max_iters": prof.max_iters,
                "phase1_iters": prof.phase1_iters,
                "loadgen_requests": prof.loadgen_requests,
                "pool_replicas": prof.pool_replicas,
                "pool_requests": prof.pool_requests,
                "plane_series": prof.plane_series,
                "resident_series": prof.resident_series,
                "refit_series": prof.refit_series,
                "sched_storm": prof.sched_storm,
                "storage_storm": prof.storage_storm,
                "fplane_storm": prof.fplane_storm,
                "qplane_storm": prof.qplane_storm,
                "alerts_storm": prof.alerts_storm,
            },
            "schedule": storm.schedule(),
            "fault_classes": sorted(storm.by_class()),
            "faults": per_class,
            "stages": {k: {kk: vv for kk, vv in v.items()
                           if kk not in ("out_dir", "end_time")}
                       for k, v in stages.items()},
            "invariants": invariants,
            "io": io_metrics,
            "mttr_s": {k: (None if v is None else round(v, 3))
                       for k, v in mttr.items()},
            "mttr_spans_s": mttr_spans,
            "ok": ok,
        }
        if ledger_path is not None:
            ledger["reports"] = [{
                "kind": report["kind"], "unix": report["unix"],
                "trace_id": report["trace_id"], "ok": report["ok"],
                "joined": True,
            }]
            report["ledger_path"] = obs_ledger.write_ledger(
                ledger, ledger_path
            )
        return report
    finally:
        obs.end_run(prev_run)
        if env_old is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = env_old
        if resident_old is None:
            os.environ.pop("BENCH_NO_RESIDENT", None)
        else:
            os.environ["BENCH_NO_RESIDENT"] = resident_old
        if own_scratch and not keep_scratch:
            shutil.rmtree(scratch, ignore_errors=True)


def write_scorecard(report: Dict, path: Optional[str] = None) -> str:
    """Persist a storm scorecard as ``CHAOS_<unix>.json`` (atomic, like
    every other report artifact)."""
    out = path or f"CHAOS_{int(report.get('unix', time.time()))}.json"
    atomic_write(out, lambda fh: json.dump(report, fh, indent=1),
                 mode="w")
    return out


def summarize(report: Dict) -> str:
    """One operator-facing line per storm (the CLI's stdout)."""
    invs = report["invariants"]
    bad = [k for k, v in invs.items() if not v.get("ok")]
    mttr = ", ".join(
        f"{k}={v}s" for k, v in sorted(report["mttr_s"].items())
        if v is not None
    )
    return (
        f"chaos storm seed={report['seed']} profile={report['profile']}: "
        f"{len(report['fault_classes'])} fault classes, "
        f"{len(invs)} invariants "
        f"{'ALL GREEN' if report['ok'] else 'FAILED: ' + ', '.join(bad)}"
        f" | mttr: {mttr or 'n/a'}"
    )
