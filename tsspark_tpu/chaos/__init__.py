"""Chaos-engineering harness (``docs/RESILIENCE.md``, "Chaos harness &
failure domains").

``python -m tsspark_tpu.chaos --seed 0`` composes a seeded, fully
deterministic fault storm (``storm.compose``) — worker kills, torn
artifact writes, spawn failures, slow-I/O stalls, wedged accelerator
probes, registry snapshot corruption, streaming poll faults, serve
dispatch faults, queue-overload bursts, activation races, pool replica
kills, front crashes, split-brain activations, torn data-plane shards,
ingest-driver kills — and drives the whole pipeline through it:
orchestrate fit workers -> registry publish/activate -> streaming
driver -> prediction engine under loadgen -> serve replica pool ->
columnar data plane.  The invariant checkers (``invariants``) then
verify the properties that make the storm a regression gate rather
than a demo:

* every series lands exactly once (coverage tiles with no gap/overlap,
  and the result is bitwise identical to a fault-free run);
* no torn artifact is ever read (CRC quarantine + atomic-write temps
  all accounted for; a corrupt active registry snapshot degrades to the
  last good version, never into forecasts);
* engine-batched forecasts stay bitwise equal to direct
  ``backend.predict`` throughout;
* the replica pool serves zero wrong-version responses and loses zero
  non-shed requests through a replica kill, exactly one process owns
  each slot lease after a steal, and a revived zombie is fenced;
* the data plane detects torn shards, repairs them bitwise, and a
  consumer self-produces a dead ingest driver's missing shards;
* recovery after each injected fault stays under the profile's budget
  (MTTR per fault class, measured off the fault harness's
  cross-process claim files).

The outcome is a ``CHAOS_*.json`` scorecard — the robustness analog of
``BENCH_*``/``SERVE_*`` — with the full injection schedule recorded, so
the same seed reproduces the same storm anywhere.
"""

from tsspark_tpu.chaos.harness import run_storm, summarize, write_scorecard
from tsspark_tpu.chaos.storm import (
    PROFILES,
    Injection,
    StormPlan,
    StormProfile,
    compose,
)

__all__ = [
    "Injection",
    "PROFILES",
    "StormPlan",
    "StormProfile",
    "compose",
    "run_storm",
    "summarize",
    "write_scorecard",
]
