"""Chaos invariant checkers: what "survived the storm" means, verified.

Each checker returns a JSON-able dict with an ``ok`` bool plus the
evidence behind it; the harness composes them into the ``CHAOS_*``
scorecard.  The four invariant families (ISSUE 5):

* **exactly-once** — every series lands exactly once: completed chunk
  ranges are pairwise disjoint AND tile ``[0, series)`` with no gap or
  overlap, and the assembled state is bitwise identical to a fault-free
  reference run (loss, duplication, or a double-landed stale result
  would all break bitwise equality).
* **no-torn-reads** — the CRC + atomic-write protocol held: every
  corruption the storm injected was quarantined (``*.corrupt``) rather
  than assembled, and no dead writer's atomic-write temp survives the
  sweeps.
* **parity** — engine-batched forecasts stay bitwise equal to a direct
  ``backend.predict`` over the same snapshot rows, throughout the storm.
* **recovery** — the measured time from each injected fault to the next
  healthy signal (MTTR) stays under the profile's budget.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from tsspark_tpu.utils.atomic import sweep_stale_temps

_STATE_FIELDS = ("theta", "loss", "grad_norm", "converged", "n_iters",
                 "status")


def coverage_exactly_once(ranges: List[Tuple[int, int]],
                          series: int) -> Dict:
    """Ranges must tile [0, series) with no gap, overlap, or overhang —
    the file-level form of "every series landed exactly once"."""
    errs: List[str] = []
    cur = 0
    for lo, hi in sorted(ranges):
        if lo < cur:
            errs.append(f"overlap at {lo} (covered through {cur}): a "
                        "series row would be assembled twice")
        elif lo > cur:
            errs.append(f"gap [{cur}, {lo}): series lost")
        cur = max(cur, hi)
    if cur < series:
        errs.append(f"gap [{cur}, {series}): series lost")
    elif cur > series:
        errs.append(f"coverage overhangs to {cur} > {series}")
    return {"ok": not errs, "series": series,
            "ranges": [list(r) for r in sorted(ranges)], "errors": errs}


def states_bitwise_equal(got, ref,
                         skip_rows: Optional[np.ndarray] = None) -> Dict:
    """Bitwise comparison of two assembled FitStates (solver outputs +
    scaling meta).  ``skip_rows``: rows excluded from the comparison
    (quarantined series, which a faulted run deliberately NaNs)."""
    n = int(np.asarray(ref.theta).shape[0])
    rows = np.ones(n, bool)
    if skip_rows is not None and len(skip_rows):
        rows[np.asarray(skip_rows, np.int64)] = False
    mismatches: List[str] = []

    def cmp(name, a, b):
        a = np.asarray(a)[rows]
        b = np.asarray(b)[rows]
        if a.shape != b.shape or not np.array_equal(a, b):
            mismatches.append(name)

    for f in _STATE_FIELDS:
        ga, rf = getattr(got, f, None), getattr(ref, f, None)
        if ga is None or rf is None:
            continue
        cmp(f, ga, rf)
    for f in ref.meta._fields:
        cmp(f"meta.{f}", getattr(got.meta, f), getattr(ref.meta, f))
    return {"ok": not mismatches, "rows_compared": int(rows.sum()),
            "mismatched_fields": mismatches}


def no_torn_reads(out_dir: str, corrupt_injected: int) -> Dict:
    """The integrity protocol's evidence after the storm: every injected
    corruption was quarantined out of the resume globs, and no dead
    writer's atomic temp survived the sweeps (a zero-age sweep here
    counts AND removes any orphan the run left behind)."""
    quarantined = sorted(
        os.path.basename(p)
        for p in glob.glob(os.path.join(out_dir, "*.corrupt"))
    )
    stale_temps = sweep_stale_temps(out_dir, max_age_s=0.0,
                                    recursive=True)
    ok = len(quarantined) >= corrupt_injected
    return {
        "ok": ok,
        "corrupt_injected": corrupt_injected,
        "quarantined": quarantined,
        "stale_temps_reaped": stale_temps,
        "errors": ([] if ok else [
            f"{corrupt_injected} corruption(s) injected but only "
            f"{len(quarantined)} quarantined file(s) found — a torn "
            "payload may have been read"
        ]),
    }


def recovery_within_budget(mttr_s: Dict[str, Optional[float]],
                           budget_s: float) -> Dict:
    """Every fault class that fired must have recovered within the
    budget; a class with no recovery signal (None) is a failure."""
    errs = []
    for cls, t in mttr_s.items():
        if t is None:
            errs.append(f"{cls}: no recovery observed")
        elif t > budget_s:
            errs.append(f"{cls}: recovered in {t:.1f}s > budget "
                        f"{budget_s:.0f}s")
    return {
        "ok": not errs,
        "budget_s": budget_s,
        "mttr_s": {k: (None if v is None else round(v, 3))
                   for k, v in mttr_s.items()},
        "errors": errs,
    }


def pool_request_integrity(counters: Dict) -> Dict:
    """The pool-stage acceptance: zero responses served at a version
    the front did not expect (split-brain / stale-read window), and
    zero non-shed request failures through the storm's replica kill —
    every transport failure must have been failed over to a sibling."""
    errs: List[str] = []
    if counters.get("wrong_version", 0):
        errs.append(
            f"{counters['wrong_version']} response(s) served at an "
            "unexpected version: the stale-read window is open"
        )
    if counters.get("failed", 0):
        errs.append(
            f"{counters['failed']} non-shed request failure(s) "
            "through the storm: a replica death cost requests that "
            "should have failed over"
        )
    if not counters.get("completed", 0):
        errs.append("no pool request completed (vacuous storm)")
    if not counters.get("failover_exercised", True):
        errs.append(
            "the post-kill probe at the dead slot's shard was not "
            "served by a sibling: failover never actually ran"
        )
    if not counters.get("fenced_probe_refused", True):
        errs.append(
            "the revived zombie replica served data instead of the "
            "structured fenced refusal (split-brain)"
        )
    return {"ok": not errs, "counters": dict(counters), "errors": errs}


def pool_single_owner(pool_dir: str,
                      replica_pids: Dict[int, Optional[int]]) -> Dict:
    """Exactly-one-owner after steals: each slot's lease must exist,
    belong to the CURRENT replica process (pid match), and that pid
    must be alive — a zombie's stale token still holding a slot, or a
    slot with no lease at all, is a routing split-brain."""
    from tsspark_tpu import orchestrate

    errs: List[str] = []
    owners: Dict[str, Optional[int]] = {}
    for slot, pid in sorted(replica_pids.items()):
        lease = orchestrate.read_lease(pool_dir, slot, slot + 1)
        lease_pid = None if lease is None else int(lease.get("pid", -1))
        owners[str(slot)] = lease_pid
        if lease is None:
            errs.append(f"slot {slot}: no lease on disk")
            continue
        if pid is not None and lease_pid != pid:
            errs.append(
                f"slot {slot}: lease owned by pid {lease_pid}, the "
                f"serving replica is pid {pid} — two processes think "
                "they own the slot"
            )
        try:
            os.kill(int(lease_pid), 0)
        except (OSError, TypeError):
            errs.append(f"slot {slot}: lease owner {lease_pid} is dead")
    return {"ok": not errs, "lease_owners": owners,
            "replica_pids": {str(k): v
                             for k, v in sorted(replica_pids.items())},
            "errors": errs}


def plane_consistent(spec, root: str) -> Dict:
    """Data-plane end state: every shard sentinel's CRC verifies
    against the memmap rows, the manifest marks the dataset complete,
    and the cached columns are BITWISE what direct generation produces
    — a torn shard that survived repair, or a self-produced shard that
    diverged from the dead driver's bytes, both break this."""
    from tsspark_tpu.data import plane

    dset_dir = plane.dataset_dir(spec, root)
    errs: List[str] = []
    if not plane.is_complete(dset_dir):
        errs.append("dataset has no complete manifest")
    for lo, hi in plane.shard_ranges(spec):
        if not plane.verify_shard(dset_dir, lo, hi):
            errs.append(f"shard [{lo}, {hi}) fails its CRC check")
    bitwise = True
    if not errs:
        batch = plane.open_batch(dset_dir)
        want = plane.batch_columns(
            plane.generate_rows(spec, 0, spec.n_series)
        )
        got = {"y": np.asarray(batch.y), "mask": np.asarray(batch.mask)}
        for f in ("y", "mask"):
            if not np.array_equal(got[f], want[f]):
                bitwise = False
                errs.append(f"column {f} diverges bitwise from direct "
                            "generation")
    return {"ok": not errs, "bitwise_vs_generation": bitwise,
            "shards": len(plane.shard_ranges(spec)), "errors": errs}


def alerts_exactly_once(expected_keys: List[str],
                        sink_alerts: List[Dict],
                        watermark: int, scored: int) -> Dict:
    """The alert stream's end state after the storm: every alert key
    the certified records expect appears in the sink EXACTLY once — no
    duplicate (a redelivery that slipped the dedup), no gap (a record
    the watermark skipped past unacked) — and the delivery watermark
    sits at the scored head (nothing certified is still undelivered).
    Kill-point placement, brownouts, and torn records all have to
    collapse into this one observable sink truth."""
    errs: List[str] = []
    delivered: Dict[str, int] = {}
    for a in sink_alerts:
        k = a.get("key")
        if k is not None:
            delivered[k] = delivered.get(k, 0) + 1
    dupes = sorted(k for k, n in delivered.items() if n > 1)
    expected = set(expected_keys)
    missing = sorted(expected - set(delivered))
    if dupes:
        errs.append(f"{len(dupes)} alert key(s) delivered more than "
                    f"once: {dupes[:4]}")
    if missing:
        errs.append(f"{len(missing)} expected alert key(s) never "
                    f"reached the sink: {missing[:4]}")
    if watermark != scored:
        errs.append(f"delivery watermark {watermark} is behind the "
                    f"scored head {scored}")
    return {
        "ok": not errs,
        "expected": len(expected),
        "delivered": len(delivered),
        "duplicates": len(dupes),
        "missing": len(missing),
        "watermark": int(watermark),
        "scored": int(scored),
        "errors": errs,
    }


def refit_unchanged_bitwise(base_vdir: str, new_vdir: str,
                            changed_rows) -> Dict:
    """Delta-publish parity: every per-series column of the NEW
    version's snapshot plane must be bitwise the base version's on the
    UNCHANGED rows (copy-forward preserved them; a scatter that bled
    into a neighboring row — or a torn copy — breaks this), and the new
    plane must pass its own CRC sentinel."""
    import json

    from tsspark_tpu.serve import snapplane

    errs: List[str] = []
    try:
        with open(os.path.join(base_vdir, snapplane.SNAP_SPEC)) as fh:
            spec = json.load(fh)
    except (OSError, ValueError) as e:
        return {"ok": False, "errors": [f"base spec unreadable: {e}"]}
    n = int(spec.get("n_series", 0))
    changed = np.unique(np.asarray(changed_rows, np.int64))
    unchanged = np.setdiff1d(np.arange(n, dtype=np.int64), changed)
    compared = []
    for name in spec.get("columns") or {}:
        try:
            base = np.load(
                os.path.join(base_vdir, f"{snapplane.COL_PREFIX}{name}.npy"),
                mmap_mode="r",
            )
            new = np.load(
                os.path.join(new_vdir, f"{snapplane.COL_PREFIX}{name}.npy"),
                mmap_mode="r",
            )
        except (OSError, ValueError) as e:
            errs.append(f"column {name}: unreadable ({e})")
            continue
        if not np.array_equal(np.asarray(base[unchanged]),
                              np.asarray(new[unchanged])):
            errs.append(
                f"column {name}: unchanged rows differ from the base "
                "version (copy-forward broke bitwise stability)"
            )
        compared.append(name)
    if not snapplane.verify_plane(new_vdir):
        errs.append("new version's plane fails its CRC sentinel")
    return {
        "ok": not errs,
        "columns_compared": compared,
        "n_unchanged": int(len(unchanged)),
        "n_changed": int(len(changed)),
        **({"errors": errs} if errs else {}),
    }


def fault_firing_times(state_dir: str, rule_cls: Dict[str, str],
                       rules: List[dict]) -> Dict[str, List[float]]:
    """Per-class wall-clock firing times, read off the fault plan's
    claim files: slot ``n`` of rule ``r`` fired iff
    ``after <= n < after + attempts`` and its claim file exists — the
    file's mtime is the moment the call was armed, no matter which
    process made it."""
    out: Dict[str, List[float]] = {}
    for rule in rules:
        cls = rule_cls.get(rule["id"])
        if cls is None:
            continue
        for n in range(rule["after"], rule["after"] + rule["attempts"]):
            path = os.path.join(state_dir, f"{rule['id']}.{n}")
            try:
                out.setdefault(cls, []).append(os.path.getmtime(path))
            except OSError:
                continue  # slot never reached: the fault did not fire
    return out


def orchestrate_mttr(fired: Dict[str, List[float]], out_dir: str,
                     end_time: float) -> Dict[str, Optional[float]]:
    """MTTR for the orchestrate-stage classes: time from each firing to
    the next chunk result landing after it (the pipeline's "healthy
    again" signal), the phase-2 sentinel, or the stage end."""
    progress = sorted(
        os.path.getmtime(p)
        for p in glob.glob(os.path.join(out_dir, "chunk_*.npz"))
    )
    marker = os.path.join(out_dir, "phase2_done")
    if os.path.exists(marker):
        progress.append(os.path.getmtime(marker))
    progress.sort()
    out: Dict[str, Optional[float]] = {}
    for cls, times in fired.items():
        worst: Optional[float] = 0.0
        for t in times:
            nxt = next((p for p in progress if p > t), None)
            if nxt is None:
                nxt = end_time if end_time > t else None
            if nxt is None:
                worst = None
                break
            worst = max(worst, nxt - t)
        out[cls] = worst
    return out
