"""Trace-safety lint: an AST pass over the package's JAX code.

JAX's tracing model makes four classes of bug invisible to CPU unit
tests but expensive (or wrong) on real hardware:

* ``trace-branch`` — Python ``if``/``while`` on a traced value.  Under
  ``jax.jit`` this either raises a ConcretizationTypeError on device or
  — worse — silently bakes one branch into the compiled program when the
  test happens to be concrete at trace time.
* ``host-sync`` — ``float()``/``int()``/``bool()``/``.item()``/
  ``.tolist()``/``np.*`` on a traced value inside a jitted scope: each
  is a device->host round trip (~40 ms on the tunneled runtime) that
  serializes the dispatch pipeline, or a trace error.
* ``f64-dtype`` — ``float64`` dtype requests inside traced code.  With
  x64 off (this package's contract) they silently produce f32; with it
  on they double every buffer and halve TPU throughput.  Flipping
  ``jax_enable_x64`` anywhere is flagged for the same reason.
* ``static-hash`` — silent-recompilation hazards: mutable default
  arguments (unhashable as jit statics, and a shared-state bug besides),
  ``static_argnames`` naming a parameter that does not exist or whose
  default is mutable, and ``jax.jit(lambda ...)`` inside a function body
  (a fresh function identity per call defeats the jit cache and
  recompiles every time).

Scope discovery is static: jit ROOTS are functions decorated with
``jax.jit`` / ``functools.partial(jax.jit, ...)`` (or wrapped via a
module-level ``name = jax.jit(fn)``); the traced set is the closure of
the intra-package call graph over those roots.  Parameters whose names
appear as ``static_argnames`` anywhere in the package (``config``,
``solver_config``, ``mesh``, ...) are treated as static in every traced
function — the package keeps its calling convention consistent, and the
committed suppression baseline absorbs the residue.

False positives are EXPECTED at the margins of any static analysis;
the contract is that each one is either fixed or explicitly justified —
inline ``# lint-ok[rule]: reason`` or a ``[tool.tsspark.analysis]``
baseline entry — so the default-on repo pass stays at zero unexplained
findings.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from tsspark_tpu.analysis.findings import Finding

_INLINE_OK = re.compile(r"#\s*lint-ok\[(?P<rule>[a-z0-9-]+)\]\s*:\s*\S")

#: (relpath, lineno, rule) triples for every inline waiver that
#: actually suppressed a finding during this process's checker runs.
#: ``line_ok`` is called exactly when a finding is about to be emitted,
#: so a site absent from this set after a full pass is a waiver
#: excusing nothing — the stale-waiver checker's raw material.  All
#: checkers built on ``_ModuleScan`` (trace, concur, effects) feed it.
WAIVER_HITS: Set[Tuple[str, int, str]] = set()


def reset_waiver_hits() -> None:
    WAIVER_HITS.clear()

# Value accessors that are STATIC under tracing (reading them off a
# tracer yields a concrete Python value at trace time, no sync).
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "_fields", "sharding"}
# Builtins whose result on a tracer is static / trace-safe.
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "type", "range"}
# Calls that force a concrete value out of a tracer (host sync / error).
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
_SYNC_METHODS = {"item", "tolist", "numpy", "__array__"}
# numpy namespace aliases whose CALLS on traced values leave the device.
_NP_ALIASES = {"np", "numpy", "onp"}
_F64_NAMES = {"float64", "double", "f8"}
# Ubiquitous builtin-container/str method names: an attribute call like
# ``stack.append(x)`` must not create a call-graph edge to every package
# function that happens to share the name, or host-side classes with a
# method called ``append``/``get``/... would be linted as traced code.
_GENERIC_METHODS = {
    "append", "extend", "insert", "pop", "remove", "sort", "clear",
    "copy", "get", "keys", "values", "items", "setdefault", "add",
    "discard", "update", "write", "read", "close", "join", "format",
    "startswith", "endswith", "strip", "encode", "decode",
}


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` reference?"""
    if isinstance(node, ast.Attribute):
        return (node.attr == "jit" and isinstance(node.value, ast.Name)
                and node.value.id == "jax")
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_call_of(node: ast.AST) -> Optional[ast.Call]:
    """The ``jax.jit(...)`` / ``partial(jax.jit, ...)`` call carried by a
    decorator or wrapper expression, else None."""
    if isinstance(node, ast.Call):
        if _is_jax_jit(node.func):
            return node
        # functools.partial(jax.jit, static_argnames=...)
        f = node.func
        is_partial = (
            (isinstance(f, ast.Attribute) and f.attr == "partial")
            or (isinstance(f, ast.Name) and f.id == "partial")
        )
        if is_partial and node.args and _is_jax_jit(node.args[0]):
            return node
    if _is_jax_jit(node):
        return ast.Call(func=node, args=[], keywords=[])
    return None


def _static_argnames(call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames" and isinstance(
            kw.value, (ast.Tuple, ast.List, ast.Constant)
        ):
            elts = (
                [kw.value] if isinstance(kw.value, ast.Constant)
                else kw.value.elts
            )
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return names


def _static_argnums(call: ast.Call) -> Set[int]:
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums" and isinstance(
            kw.value, (ast.Tuple, ast.List, ast.Constant)
        ):
            elts = (
                [kw.value] if isinstance(kw.value, ast.Constant)
                else kw.value.elts
            )
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.add(e.value)
    return nums


class _FnInfo:
    """One function definition's lint-relevant facts."""

    def __init__(self, qualname: str, node: ast.FunctionDef,
                 jit_call: Optional[ast.Call]):
        self.qualname = qualname
        self.node = node
        self.jit_call = jit_call
        self.calls: Set[str] = set()   # local names this function calls
        # Callees resolved by QUALIFIED name through the module's
        # imports: (dotted module path, function name).  These join
        # precisely in the traced closure instead of by simple name —
        # the DatasetSpec.key -> cache_key rename class: two functions
        # sharing a simple name in different modules must not pull each
        # other into (or keep each other in) the traced set.
        self.qual_calls: Set[Tuple[str, str]] = set()
        args = node.args
        self.param_names = [a.arg for a in args.posonlyargs + args.args
                            + args.kwonlyargs]
        self.static_params: Set[str] = set()
        if jit_call is not None:
            self.static_params |= _static_argnames(jit_call)
            for i in _static_argnums(jit_call):
                if i < len(self.param_names):
                    self.static_params.add(self.param_names[i])


class _ModuleScan:
    def __init__(self, relpath: str, tree: ast.Module, source: str):
        self.relpath = relpath
        self.tree = tree
        self.lines = source.splitlines()
        self.functions: Dict[str, _FnInfo] = {}
        #: local name -> dotted module path (``import x.y as z``)
        self.imports: Dict[str, str] = {}
        #: local name -> (dotted module path, original name) for
        #: ``from x.y import f [as g]``
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self._collect_imports()

    def _collect_imports(self) -> None:
        pkg_parts = self.relpath.replace(os.sep, "/").split("/")[:-1]
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # Relative import: resolve against this module's
                    # package path (level 1 = the current package).
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    mod = ".".join(base + ([node.module]
                                           if node.module else []))
                else:
                    mod = node.module or ""
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = (
                        mod, alias.name
                    )

    def line_ok(self, lineno: int, rule: str) -> bool:
        if 1 <= lineno <= len(self.lines):
            m = _INLINE_OK.search(self.lines[lineno - 1])
            if m and m.group("rule") == rule:
                WAIVER_HITS.add((self.relpath, lineno, rule))
                return True
        return False


def _walk_functions(scan: _ModuleScan) -> None:
    """Collect every function def (module-level and nested/methods) with
    its jit decoration and outgoing call names."""

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                jit_call = None
                for dec in child.decorator_list:
                    jit_call = jit_call or _jit_call_of(dec)
                info = _FnInfo(qual, child, jit_call)
                # Locally-bound names (params + any Store target):
                # a local passed as an argument is DATA, not a function
                # reference — it must not manufacture a simple-name
                # edge to an unrelated package function (`span = t1 -
                # t0` joining obs.context.span was exactly this).
                local_names: Set[str] = set(info.param_names)
                for sub in ast.walk(child):
                    if isinstance(sub, ast.Name) \
                            and isinstance(sub.ctx, ast.Store):
                        local_names.add(sub.id)
                for sub in ast.walk(child):
                    if isinstance(sub, ast.Call):
                        if isinstance(sub.func, ast.Name):
                            name = sub.func.id
                            if name in scan.from_imports:
                                # ``from mod import f``: resolve to mod
                                # precisely, never by simple name.
                                info.qual_calls.add(
                                    scan.from_imports[name]
                                )
                            else:
                                info.calls.add(name)
                        elif isinstance(sub.func, ast.Attribute) \
                                and sub.func.attr not in _GENERIC_METHODS:
                            recv = sub.func.value
                            if isinstance(recv, ast.Name) \
                                    and recv.id in scan.imports:
                                # ``mod.f(...)``: qualified edge into
                                # the imported module only (and no edge
                                # at all into the package when the
                                # module is external — np.argsort must
                                # not join a package fn named argsort).
                                info.qual_calls.add(
                                    (scan.imports[recv.id],
                                     sub.func.attr)
                                )
                            elif isinstance(recv, ast.Subscript):
                                # ``x.at[i].set(v)`` — JAX's functional
                                # update; a subscripted receiver is
                                # never a package module, so a simple-
                                # name edge here only manufactures
                                # collisions (Gauge.set et al.).
                                pass
                            else:
                                info.calls.add(sub.func.attr)
                        # Function REFERENCES passed as arguments — the
                        # lax.while_loop(cond, body, ...) callback idiom;
                        # those callees run traced just like direct calls.
                        for a in list(sub.args) + [
                            kw.value for kw in sub.keywords
                        ]:
                            if isinstance(a, ast.Name):
                                if a.id in scan.from_imports:
                                    info.qual_calls.add(
                                        scan.from_imports[a.id]
                                    )
                                elif a.id not in local_names:
                                    info.calls.add(a.id)
                scan.functions[qual] = info
                visit(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.Import, ast.ImportFrom)):
                continue
            else:
                visit(child, prefix)

    visit(scan.tree, "")
    # Module-level jit wrappers: name = jax.jit(fn) marks fn as a root.
    for stmt in scan.tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            call = _jit_call_of(stmt.value)
            if call is None and _is_jax_jit(stmt.value.func):
                call = stmt.value
            if call is not None:
                for a in stmt.value.args:
                    if isinstance(a, ast.Name) and a.id in scan.functions:
                        scan.functions[a.id].jit_call = call
                        info = scan.functions[a.id]
                        info.static_params |= _static_argnames(call)


def _traced_closure(scans: List[_ModuleScan]) -> Set[Tuple[str, str]]:
    """(relpath, qualname) of every function statically reachable from a
    jit root — qualified-import edges join precisely; simple-name calls
    (methods, locals, references) fall back to joining every function
    sharing the name."""
    by_name: Dict[str, List[Tuple[str, str]]] = {}
    by_module: Dict[str, List[Tuple[str, str]]] = {}
    for scan in scans:
        mod = scan.relpath.replace(os.sep, "/")
        mod = mod[:-3] if mod.endswith(".py") else mod
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        dotted = mod.replace("/", ".")
        for qual, info in scan.functions.items():
            by_name.setdefault(qual.rsplit(".", 1)[-1], []).append(
                (scan.relpath, qual)
            )
            by_module.setdefault(dotted, []).append(
                (scan.relpath, qual)
            )
    info_of = {
        (scan.relpath, qual): info
        for scan in scans for qual, info in scan.functions.items()
    }

    def resolve_qual(mod: str, name: str) -> List[Tuple[str, str]]:
        """Functions named ``name`` inside the scanned module ``mod``.
        When the module is scanned but defines no such function (a
        package ``__init__`` RE-EXPORTING it), fall back to the
        simple-name join — dropping the edge would un-lint traced code.
        A module outside the scan (numpy, jax) yields no edge at all:
        ``np.argsort`` must not join a package function named argsort."""
        hits = [
            key for key in by_module.get(mod, ())
            if key[1] == name or key[1].endswith("." + name)
        ]
        if hits:
            return hits
        internal = mod in by_module or any(
            k.startswith(mod + ".") for k in by_module
        )
        return list(by_name.get(name, ())) if internal else []

    traced: Set[Tuple[str, str]] = {
        key for key, info in info_of.items() if info.jit_call is not None
    }
    frontier = list(traced)
    while frontier:
        key = frontier.pop()
        new = set()
        for callee in info_of[key].calls:
            new.update(by_name.get(callee, ()))
        for mod, name in info_of[key].qual_calls:
            new.update(resolve_qual(mod, name))
        # Nested defs of a traced function run traced (the while_loop
        # body / line-search closure pattern) even when only ever passed
        # by reference through names the call-graph cannot resolve.
        relpath, qual = key
        new.update(
            k for k in info_of
            if k[0] == relpath and k[1].startswith(qual + ".")
        )
        for target in new:
            if target not in traced:
                traced.add(target)
                frontier.append(target)
    return traced


def _collect_package_static_names(scans: List[_ModuleScan]) -> Set[str]:
    names: Set[str] = set()
    for scan in scans:
        for info in scan.functions.values():
            if info.jit_call is not None:
                names |= _static_argnames(info.jit_call)
    return names


def _value_refs(test: ast.AST, traced_names: Set[str]) -> List[str]:
    """Traced-parameter names referenced BY VALUE in an expression —
    excluding static accessors (``x.shape``, ``len(x)``, ``x is None``)
    whose results are concrete at trace time."""
    refs: List[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return  # x.shape[...] etc: static, don't descend into x
            visit(node.value)
            return
        if isinstance(node, ast.Call):
            fname = None
            if isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname in _STATIC_CALLS:
                return
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                visit(a)
            if not isinstance(node.func, ast.Name):
                visit(node.func)
            return
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` tests structure, not value.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) \
                    and all(
                        isinstance(c, ast.Constant) and c.value is None
                        for c in node.comparators
                    ):
                return
            visit(node.left)
            for c in node.comparators:
                visit(c)
            return
        if isinstance(node, ast.Name) and node.id in traced_names:
            refs.append(node.id)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(test)
    return refs


_MUTABLE_DEFAULT = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                    ast.DictComp, ast.SetComp)


def _check_fn_body(scan: _ModuleScan, info: _FnInfo,
                   package_static: Set[str],
                   findings: List[Finding]) -> None:
    """The traced-scope rules over one function body (nested defs are
    linted through their own _FnInfo; their statements are excluded
    here so a finding is attributed to the innermost function)."""
    own_static = info.static_params | package_static
    traced_names = {p for p in info.param_names
                    if p not in own_static and p != "self"}

    def emit(rule: str, node: ast.AST, msg: str) -> None:
        line = getattr(node, "lineno", info.node.lineno)
        if not scan.line_ok(line, rule):
            findings.append(Finding(rule, scan.relpath, line,
                                    info.qualname, msg))

    nested: Set[ast.AST] = set()
    for sub in ast.walk(info.node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and sub is not info.node:
            nested.update(ast.walk(sub))

    for sub in ast.walk(info.node):
        if sub in nested:
            continue
        if isinstance(sub, (ast.If, ast.While)):
            for name in _value_refs(sub.test, traced_names):
                emit(
                    "trace-branch", sub,
                    f"Python branch on traced value {name!r} (under jit "
                    "this is a ConcretizationTypeError on device, or "
                    "silently bakes one branch into the program; use "
                    "jnp.where / lax.cond)",
                )
        elif isinstance(sub, ast.Call):
            fname = sub.func.id if isinstance(sub.func, ast.Name) else None
            attr = sub.func.attr if isinstance(sub.func, ast.Attribute) \
                else None
            arg_refs = [
                r for a in list(sub.args)
                + [kw.value for kw in sub.keywords]
                for r in _value_refs(a, traced_names)
            ]
            if fname in _SYNC_BUILTINS and arg_refs:
                emit(
                    "host-sync", sub,
                    f"{fname}() on traced value {arg_refs[0]!r} forces a "
                    "device->host sync (or a trace error) inside a "
                    "jitted scope",
                )
            elif attr in _SYNC_METHODS and _value_refs(
                sub.func.value, traced_names
            ):
                emit(
                    "host-sync", sub,
                    f".{attr}() on a traced value is a host sync inside "
                    "a jitted scope",
                )
            elif (
                attr is not None
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id in _NP_ALIASES
                and arg_refs
            ):
                emit(
                    "host-sync", sub,
                    f"np.{attr}() applied to traced value "
                    f"{arg_refs[0]!r}: numpy pulls the buffer to host "
                    "(use jnp inside jitted code)",
                )
        if isinstance(sub, ast.Attribute) and sub.attr in _F64_NAMES \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id in (_NP_ALIASES | {"jnp", "jax"}):
            emit(
                "f64-dtype", sub,
                f"{sub.value.id}.{sub.attr} inside a traced scope: with "
                "x64 off this silently becomes f32; with it on it "
                "doubles every buffer (keep kernels f32 end-to-end)",
            )
        if isinstance(sub, ast.Constant) and sub.value == "float64":
            emit(
                "f64-dtype", sub,
                "string dtype 'float64' inside a traced scope (see "
                "f64 policy: kernels are f32 end-to-end)",
            )


def _check_static_hash(scan: _ModuleScan, info: _FnInfo,
                       findings: List[Finding]) -> None:
    node = info.node

    def emit(rule: str, n: ast.AST, msg: str) -> None:
        line = getattr(n, "lineno", node.lineno)
        if not scan.line_ok(line, rule):
            findings.append(Finding(rule, scan.relpath, line,
                                    info.qualname, msg))

    args = node.args
    pos = args.posonlyargs + args.args
    defaults = [None] * (len(pos) - len(args.defaults)) + list(args.defaults)
    mutable_defaults = {
        p.arg for p, d in zip(pos, defaults)
        if isinstance(d, _MUTABLE_DEFAULT)
    }
    for p, d in zip(pos, defaults):
        if isinstance(d, _MUTABLE_DEFAULT):
            emit(
                "static-hash", d,
                f"mutable default for parameter {p.arg!r} (shared across "
                "calls; unhashable if the parameter is ever a jit "
                "static — use None or a tuple)",
            )
    for kw_p, d in zip(args.kwonlyargs, args.kw_defaults):
        if isinstance(d, _MUTABLE_DEFAULT):
            mutable_defaults.add(kw_p.arg)
            emit(
                "static-hash", d,
                f"mutable default for parameter {kw_p.arg!r} (shared "
                "across calls; unhashable if the parameter is ever a "
                "jit static — use None or a tuple)",
            )
    if info.jit_call is not None:
        declared = set(info.param_names)
        for name in _static_argnames(info.jit_call):
            if name not in declared:
                emit(
                    "static-hash", info.jit_call,
                    f"static_argnames names {name!r}, which is not a "
                    f"parameter of {node.name} (jit raises at first "
                    "call — or worse, a rename left a stale static)",
                )
            elif name in mutable_defaults:
                emit(
                    "static-hash", info.jit_call,
                    f"static parameter {name!r} has a mutable default: "
                    "unhashable -> TypeError at dispatch, and near-miss "
                    "values recompile silently",
                )
    # jax.jit(lambda ...) inside a function body: new identity per call.
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _is_jax_jit(sub.func) and sub.args:
            if isinstance(sub.args[0], ast.Lambda):
                emit(
                    "static-hash", sub,
                    "jax.jit(lambda ...) inside a function body creates "
                    "a fresh jit cache entry per call — every invocation "
                    "recompiles; hoist the jitted function to module "
                    "scope",
                )


def lint_paths(
    paths: List[str], root: str,
    package_static: Optional[Set[str]] = None,
) -> List[Finding]:
    """Lint the given Python files; paths are reported relative to
    ``root``.  ``package_static`` extends the static-parameter-name set
    (the package scan seeds it from every jit decoration found)."""
    scans: List[_ModuleScan] = []
    findings: List[Finding] = []
    for path in paths:
        with open(path, "r") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                "parse-error", os.path.relpath(path, root),
                e.lineno or 0, "<module>", str(e),
            ))
            continue
        scan = _ModuleScan(os.path.relpath(path, root), tree, source)
        _walk_functions(scan)
        scans.append(scan)

    static_names = set(package_static or ())
    static_names |= _collect_package_static_names(scans)
    traced = _traced_closure(scans)

    for scan in scans:
        for qual, info in scan.functions.items():
            _check_static_hash(scan, info, findings)
            if (scan.relpath, qual) in traced:
                _check_fn_body(scan, info, static_names, findings)
        # x64 flips are a package-wide hazard regardless of scope.
        for sub in ast.walk(scan.tree):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "update" and sub.args
                    and isinstance(sub.args[0], ast.Constant)
                    and sub.args[0].value == "jax_enable_x64"):
                if not scan.line_ok(sub.lineno, "f64-dtype"):
                    findings.append(Finding(
                        "f64-dtype", scan.relpath, sub.lineno, "<module>",
                        "jax_enable_x64 flip: global dtype semantics "
                        "change under every caller (the package contract "
                        "is f32 kernels + f64 host meta)",
                    ))
    return findings


def lint_package(root: str, package_dir: str) -> List[Finding]:
    """Lint every ``.py`` under ``package_dir`` (the shipped package —
    tests and benches host-side code are out of scope by design)."""
    paths = []
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))
    return lint_paths(sorted(paths), root)


def package_static_names(package_dir: str) -> Set[str]:
    """The package-wide static-parameter-name set from a light parse of
    every module — seeds ``lint_paths`` in ``--changed`` fast mode so a
    scoped lint keeps the full calling-convention context."""
    names: Set[str] = set()
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r") as fh:
                    source = fh.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError):
                continue
            scan = _ModuleScan(os.path.relpath(path, package_dir),
                               tree, source)
            _walk_functions(scan)
            names |= _collect_package_static_names([scan])
    return names
