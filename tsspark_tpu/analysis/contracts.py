"""Abstract shape/dtype contract checker for the registered jitted kernels.

``jax.eval_shape`` traces a kernel with ``ShapeDtypeStruct`` inputs —
zero FLOPs, zero device time, no XLA compile — and returns the abstract
outputs.  Driving every registered kernel across the committed
``[tool.tsspark.analysis] kernel_matrix`` of (batch, length,
changepoints, regressors, mesh) shapes proves, on CPU and in seconds:

* the output SHAPES match the documented contracts (theta ``(B, P)``,
  packed stats ``(5, B)``, ...) for every shape the fleet dispatches;
* no kernel LEAKS float64 (or int64) into any output leaf — the classic
  f32-on-TPU drift bug where one host-side f64 scalar silently promotes
  a whole result tree;
* the sharded programs trace under every supported mesh layout (shape
  errors in sharding constraints surface at trace time, not on an
  8-chip reservation).

The registry is data: tests inject broken kernels to prove the checker
catches contract violations, and new kernels register by adding a
``KernelContract``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

from tsspark_tpu.analysis.config import KernelMatrix
from tsspark_tpu.analysis.findings import Finding

# Dtypes that must never appear in a kernel output leaf: x64 is off by
# package contract, so their presence means a weak-type promotion or an
# explicit f64 request survived into traced code.
_BANNED_DTYPES = ("float64", "complex128", "int64", "uint64")


@dataclasses.dataclass(frozen=True)
class ShapeCase:
    """One point of the kernel matrix."""

    b: int                                # series batch
    t: int                                # time-grid length
    n_cp: int                             # changepoints
    r: int                                # external regressors
    mesh_shape: Optional[Tuple[int, int]] = None  # (series, time) shards

    @property
    def label(self) -> str:
        mesh = (f" mesh={self.mesh_shape[0]}x{self.mesh_shape[1]}"
                if self.mesh_shape else "")
        return f"B={self.b} T={self.t} cp={self.n_cp} r={self.r}{mesh}"


@dataclasses.dataclass(frozen=True)
class KernelContract:
    """One kernel's abstract check.

    ``run(case)`` returns the ``jax.eval_shape`` result pytree;
    ``check(case, out)`` returns human-readable violations (the banned-
    dtype sweep over every leaf runs regardless, so ``check`` only
    asserts kernel-specific shapes).  ``wants_mesh`` routes the case
    grid: mesh kernels run once per mesh shape, others once with
    ``mesh_shape=None``.
    """

    name: str
    run: Callable[[ShapeCase], Any]
    check: Callable[[ShapeCase, Any], List[str]] = lambda case, out: []
    wants_mesh: bool = False


def _configs(case: ShapeCase):
    from tsspark_tpu.config import (
        ProphetConfig, RegressorConfig, SeasonalityConfig, SolverConfig,
    )

    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=case.n_cp,
        regressors=tuple(
            RegressorConfig(f"x{i}") for i in range(case.r)
        ),
    )
    # Shallow solver: trace structure is depth-independent (the solve is
    # a while_loop), so the cheap setting checks the same contracts.
    return cfg, SolverConfig(max_iters=8)


def _sds(shape, dtype="float32"):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _fit_data(case: ShapeCase, cfg):
    from tsspark_tpu.models.prophet.design import FitData

    f = cfg.num_features
    return FitData(
        t=_sds((case.b, case.t)),
        y=_sds((case.b, case.t)),
        mask=_sds((case.b, case.t)),
        s=_sds((case.b, case.n_cp)),
        cap=_sds((case.b, case.t)),
        X_season=_sds((case.t, cfg.num_seasonal_features)),
        X_reg=_sds((case.b, case.t, case.r)),
        prior_scales=_sds((f,)),
        mult_mask=_sds((f,)),
    )


def _packed_data(case: ShapeCase, cfg):
    from tsspark_tpu.models.prophet.design import PackedFitData

    f = cfg.num_features
    return PackedFitData(
        y=_sds((case.b, case.t)),
        ds_rel=_sds((case.t,)),
        t_off=_sds((case.b,)),
        t_inv_span=_sds((case.b,)),
        s=_sds((case.b, case.n_cp)),
        cap=_sds((case.b, 1)),
        X_season=_sds((case.t, cfg.num_seasonal_features)),
        X_reg=_sds((case.b, case.t, case.r)),
        X_reg_bits=_sds((case.b, (case.t + 7) // 8, 0), "uint8"),
        prior_scales=_sds((f,)),
        mult_mask=_sds((f,)),
    )


def _leaf_items(out) -> List[Tuple[str, Any]]:
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path(out)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in leaves]


def _expect(out_field, shape, dtype, what: str) -> List[str]:
    errs = []
    if tuple(out_field.shape) != tuple(shape):
        errs.append(f"{what}: shape {tuple(out_field.shape)} != "
                    f"expected {tuple(shape)}")
    if dtype is not None and str(out_field.dtype) != dtype:
        errs.append(f"{what}: dtype {out_field.dtype} != expected {dtype}")
    return errs


def _check_result(case: ShapeCase, cfg, res) -> List[str]:
    """LbfgsResult contract: the per-series solver outputs."""
    p = cfg.num_params
    return (
        _expect(res.theta, (case.b, p), "float32", "theta")
        + _expect(res.f, (case.b,), "float32", "f")
        + _expect(res.grad_norm, (case.b,), "float32", "grad_norm")
        + _expect(res.converged, (case.b,), "bool", "converged")
        + _expect(res.n_iters, (case.b,), "int32", "n_iters")
        + _expect(res.status, (case.b,), "int32", "status")
    )


# ---- the registered kernels ------------------------------------------------


def _k_fit_core(case: ShapeCase):
    import jax

    from tsspark_tpu.models.prophet.model import fit_core

    cfg, solver = _configs(case)
    return jax.eval_shape(
        lambda d: fit_core(d, None, cfg, solver), _fit_data(case, cfg)
    )


def _c_fit_core(case: ShapeCase, out) -> List[str]:
    cfg, _ = _configs(case)
    return _check_result(case, cfg, out)


def _k_fit_core_packed(case: ShapeCase):
    import jax

    from tsspark_tpu.models.prophet.model import fit_core_packed

    cfg, solver = _configs(case)
    theta0 = _sds((case.b, cfg.num_params))
    return jax.eval_shape(
        lambda p, th: fit_core_packed(p, th, cfg, solver,
                                      reg_u8_cols=()),
        _packed_data(case, cfg), theta0,
    )


def _c_fit_core_packed(case: ShapeCase, out) -> List[str]:
    cfg, _ = _configs(case)
    theta, stats = out
    return (
        _expect(theta, (case.b, cfg.num_params), "float32", "theta")
        + _expect(stats, (5, case.b), "float32", "stats")
    )


def _k_fit_segment(case: ShapeCase):
    import jax

    from tsspark_tpu.models.prophet.model import (
        fit_init_core, fit_segment_core,
    )

    cfg, solver = _configs(case)
    data = _fit_data(case, cfg)
    state = jax.eval_shape(lambda d: fit_init_core(d, None, cfg, solver),
                           data)
    # The segment must round-trip the FULL LbfgsState unchanged — that
    # is what makes chained segments bit-equal to one fit_core run.
    state2 = jax.eval_shape(
        lambda d, s: fit_segment_core(d, s, cfg, solver, 4), data, state
    )
    return {"init": state, "segment": state2}


def _c_fit_segment(case: ShapeCase, out) -> List[str]:
    errs = []
    init, seg = out["init"], out["segment"]
    for field in type(init)._fields:
        a, b = getattr(init, field), getattr(seg, field)
        if tuple(a.shape) != tuple(b.shape) or str(a.dtype) != str(b.dtype):
            errs.append(
                f"LbfgsState.{field}: segment changed the state contract "
                f"({a.shape}/{a.dtype} -> {b.shape}/{b.dtype}); chained "
                "segments would diverge from fit_core"
            )
    return errs


def _k_design_unpack(case: ShapeCase):
    import jax

    from tsspark_tpu.models.prophet.design import unpack_fit_data

    cfg, _ = _configs(case)
    return jax.eval_shape(
        lambda p: unpack_fit_data(p, ()), _packed_data(case, cfg)
    )


def _c_design_unpack(case: ShapeCase, out) -> List[str]:
    cfg, _ = _configs(case)
    return (
        _expect(out.t, (case.b, case.t), "float32", "t")
        + _expect(out.y, (case.b, case.t), "float32", "y")
        + _expect(out.mask, (case.b, case.t), "float32", "mask")
        + _expect(out.X_reg, (case.b, case.t, case.r), "float32", "X_reg")
    )


def _k_loss(case: ShapeCase):
    import jax

    from tsspark_tpu.models.prophet.loss import value_and_grad_batch

    cfg, _ = _configs(case)
    theta = _sds((case.b, cfg.num_params))
    return jax.eval_shape(
        lambda th, d: value_and_grad_batch(th, d, cfg),
        theta, _fit_data(case, cfg),
    )


def _c_loss(case: ShapeCase, out) -> List[str]:
    cfg, _ = _configs(case)
    f, g = out
    return (
        _expect(f, (case.b,), "float32", "loss value")
        + _expect(g, (case.b, cfg.num_params), "float32", "loss grad")
    )


def _k_trend(case: ShapeCase):
    import jax

    from tsspark_tpu.models.prophet.trend import piecewise_linear

    return jax.eval_shape(
        piecewise_linear,
        _sds((case.b, case.t)), _sds((case.b,)), _sds((case.b,)),
        _sds((case.b, case.n_cp)), _sds((case.b, case.n_cp)),
    )


def _c_trend(case: ShapeCase, out) -> List[str]:
    return _expect(out, (case.b, case.t), "float32", "trend")


def _k_seasonality(case: ShapeCase):
    import jax

    from tsspark_tpu.models.prophet.seasonality import fourier_features

    return jax.eval_shape(
        lambda t: fourier_features(t, 7.0, 3), _sds((case.b, case.t))
    )


def _c_seasonality(case: ShapeCase, out) -> List[str]:
    return _expect(out, (case.b, case.t, 6), "float32",
                   "fourier features")


def _k_mcmc(case: ShapeCase):
    import jax

    from tsspark_tpu.config import McmcConfig
    from tsspark_tpu.models.prophet.model import mcmc_core

    cfg, _ = _configs(case)
    mcfg = McmcConfig(num_samples=4, num_warmup=2, num_leapfrog=2)
    theta = _sds((case.b, cfg.num_params))
    key = _sds((2,), "uint32")
    return jax.eval_shape(
        lambda d, th, k: mcmc_core(d, th, k, cfg, mcfg),
        _fit_data(case, cfg), theta, key,
    ), mcfg


def _c_mcmc(case: ShapeCase, out) -> List[str]:
    cfg, _ = _configs(case)
    res, mcfg = out
    return _expect(
        res.samples, (mcfg.num_samples, case.b, cfg.num_params),
        "float32", "mcmc samples",
    )


def _lbfgs_state_sds(case: ShapeCase, cfg, solver):
    from tsspark_tpu.ops.lbfgs import LbfgsState

    b, p, m = case.b, cfg.num_params, solver.history
    return LbfgsState(
        theta=_sds((b, p)), f=_sds((b,)), grad=_sds((b, p)),
        s_hist=_sds((m, b, p)), y_hist=_sds((m, b, p)),
        rho=_sds((m, b)),
        iteration=_sds((), "int32"),
        converged=_sds((b,), "bool"),
        n_iters=_sds((b,), "int32"),
        prev_step=_sds((b,)),
        floor_count=_sds((b,), "int32"),
        ftol_count=_sds((b,), "int32"),
        status=_sds((b,), "int32"),
        precond=_sds((b, p)),
    )


def _k_compact_gather(case: ShapeCase):
    """The compaction scheduler's gather kernels (perf tentpole): a
    row-subset take over the solver state and the design tensors must
    preserve every dtype and reduce exactly the series axis — a drifted
    leaf here would silently corrupt every compacted trajectory."""
    import jax

    from tsspark_tpu.models.prophet.design import take_fit_data
    from tsspark_tpu.ops.lbfgs import take_state

    cfg, solver = _configs(case)
    idx = _sds((max(case.b // 2, 1),), "int32")
    return {
        "state": jax.eval_shape(
            take_state, _lbfgs_state_sds(case, cfg, solver), idx
        ),
        "data": jax.eval_shape(
            take_fit_data, _fit_data(case, cfg), idx
        ),
    }


def _c_compact_gather(case: ShapeCase, out) -> List[str]:
    cfg, solver = _configs(case)
    k = max(case.b // 2, 1)
    p, m = cfg.num_params, solver.history
    st, d = out["state"], out["data"]
    errs = (
        _expect(st.theta, (k, p), "float32", "take_state theta")
        + _expect(st.s_hist, (m, k, p), "float32", "take_state s_hist")
        + _expect(st.rho, (m, k), "float32", "take_state rho")
        + _expect(st.iteration, (), "int32", "take_state iteration")
        + _expect(st.converged, (k,), "bool", "take_state converged")
        + _expect(st.n_iters, (k,), "int32", "take_state n_iters")
        + _expect(st.status, (k,), "int32", "take_state status")
        + _expect(d.y, (k, case.t), "float32", "take_fit_data y")
        + _expect(d.X_reg, (k, case.t, case.r), "float32",
                  "take_fit_data X_reg")
    )
    # Shared leaves must stay shared: gathering the (T, Fs) calendar
    # seasonal matrix per-series would silently B-fold the design bytes.
    if tuple(d.X_season.shape) != (case.t, cfg.num_seasonal_features):
        errs.append(
            f"take_fit_data X_season: shared (T, Fs) leaf changed shape "
            f"to {tuple(d.X_season.shape)}"
        )
    return errs


def _k_warm_gather(case: ShapeCase):
    """The delta-refit warm-start gather (refit.warm_theta_gather): a
    row-subset take over the active snapshot's theta that must stay
    float32 under x64 drift — a leaked f64 init would double every
    warm wave's transfer AND flip fit_resident_core's traced input
    dtype, recompiling (or poisoning) the shared warm/cold program."""
    import jax

    cfg, _ = _configs(case)
    from tsspark_tpu.refit import warm_theta_gather

    theta = _sds((case.b, cfg.num_params))
    idx = _sds((max(case.b // 2, 1),), "int32")
    return jax.eval_shape(warm_theta_gather, theta, idx)


def _c_warm_gather(case: ShapeCase, out) -> List[str]:
    cfg, _ = _configs(case)
    k = max(case.b // 2, 1)
    return _expect(out, (k, cfg.num_params), "float32",
                   "warm_theta_gather rows")


def _k_forecast(case: ShapeCase):
    """The batched predict entry point the serving engine dispatches
    through (predict.forecast_jit): traced with sampling ON so the
    trend-path simulation and quantile reduction are inside the checked
    program — the path where an un-pinned random-draw dtype doubles
    every sample tensor under x64 drift."""
    import jax

    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.predict import forecast

    cfg, _ = _configs(case)
    theta = _sds((case.b, cfg.num_params))
    meta = ScalingMeta(
        y_scale=_sds((case.b,)), floor=_sds((case.b,)),
        ds_start=_sds((case.b,)), ds_span=_sds((case.b,)),
        reg_mean=_sds((case.b, case.r)), reg_std=_sds((case.b, case.r)),
        changepoints=_sds((case.b, case.n_cp)),
    )
    key = _sds((2,), "uint32")
    return jax.eval_shape(
        lambda th, d, m, k: forecast(th, d, m, cfg, key=k,
                                     num_samples=4),
        theta, _fit_data(case, cfg), meta, key,
    )


def _c_forecast(case: ShapeCase, out) -> List[str]:
    errs = []
    for name in ("yhat", "trend", "additive", "multiplicative",
                 "yhat_lower", "yhat_upper", "trend_lower",
                 "trend_upper"):
        if name not in out:
            errs.append(f"forecast output lacks {name!r}")
            continue
        errs += _expect(out[name], (case.b, case.t), "float32", name)
    return errs


def _mesh_for(case: ShapeCase):
    import jax

    from tsspark_tpu.parallel import mesh as mesh_mod

    n_s, n_t = case.mesh_shape
    if len(jax.devices()) < n_s * n_t:
        return None
    return mesh_mod.make_mesh(
        n_series_shards=n_s, n_time_shards=n_t,
        devices=jax.devices()[: n_s * n_t],
    )


def _k_sharded(case: ShapeCase):
    import jax

    from tsspark_tpu.config import ShardingConfig
    from tsspark_tpu.parallel.sharding import _fit_sharded_core

    cfg, solver = _configs(case)
    mesh = _mesh_for(case)
    if mesh is None:
        return None
    shard_cfg = ShardingConfig(
        time_axis="time" if case.mesh_shape[1] > 1 else None
    )
    theta0 = _sds((case.b, cfg.num_params))
    return jax.eval_shape(
        lambda d, th: _fit_sharded_core(d, th, cfg, solver, mesh,
                                        shard_cfg),
        _fit_data(case, cfg), theta0,
    )


def _k_sharded_packed(case: ShapeCase):
    import jax

    from tsspark_tpu.config import ShardingConfig
    from tsspark_tpu.parallel.sharding import _fit_sharded_packed_core

    cfg, solver = _configs(case)
    mesh = _mesh_for(case)
    if mesh is None:
        return None
    shard_cfg = ShardingConfig(
        time_axis="time" if case.mesh_shape[1] > 1 else None
    )
    theta0 = _sds((case.b, cfg.num_params))
    return jax.eval_shape(
        lambda p, th: _fit_sharded_packed_core(
            p, th, cfg, solver, mesh, shard_cfg, ()
        ),
        _packed_data(case, cfg), theta0,
    )


def _c_sharded(case: ShapeCase, out) -> List[str]:
    cfg, _ = _configs(case)
    return _check_result(case, cfg, out)


def _k_resident(case: ShapeCase):
    """The mesh-resident fit program (tsspark_tpu.resident): traced with
    the phase-control triple the resident waves actually pass, so the
    one-program-for-both-phases contract is checked abstractly on every
    mesh layout of the matrix (the contract does not need real sharded
    placement — eval_shape proves shapes/dtypes for the traced body,
    which is pinned to fit_core_packed's)."""
    import jax
    import numpy as np

    from tsspark_tpu.parallel.sharding import fit_resident_core

    cfg, solver = _configs(case)
    if _mesh_for(case) is None:
        return None
    theta0 = _sds((case.b, cfg.num_params))
    return jax.eval_shape(
        lambda p, th: fit_resident_core(
            p, th, cfg, solver, (),
            max_iters_dynamic=np.int32(6),
            gn_precond_dynamic=np.bool_(False),
            use_theta0_dynamic=np.bool_(False),
        ),
        _packed_data(case, cfg), theta0,
    )


def _c_resident(case: ShapeCase, out) -> List[str]:
    cfg, _ = _configs(case)
    theta, stats = out
    return (
        _expect(theta, (case.b, cfg.num_params), "float32", "theta")
        + _expect(stats, (5, case.b), "float32", "stats")
    )


def default_kernels() -> Tuple[KernelContract, ...]:
    return (
        KernelContract("model.fit_core", _k_fit_core, _c_fit_core),
        KernelContract("model.fit_core_packed", _k_fit_core_packed,
                       _c_fit_core_packed),
        KernelContract("model.fit_segment", _k_fit_segment,
                       _c_fit_segment),
        KernelContract("design.unpack_fit_data", _k_design_unpack,
                       _c_design_unpack),
        KernelContract("loss.value_and_grad_batch", _k_loss, _c_loss),
        KernelContract("trend.piecewise_linear", _k_trend, _c_trend),
        KernelContract("seasonality.fourier_features", _k_seasonality,
                       _c_seasonality),
        KernelContract("model.mcmc_core", _k_mcmc, _c_mcmc),
        KernelContract("compact.take_state+take_fit_data",
                       _k_compact_gather, _c_compact_gather),
        KernelContract("refit.warm_theta_gather", _k_warm_gather,
                       _c_warm_gather),
        KernelContract("predict.forecast (serve batched entry)",
                       _k_forecast, _c_forecast),
        KernelContract("sharding.fit_sharded", _k_sharded, _c_sharded,
                       wants_mesh=True),
        KernelContract("sharding.fit_sharded_packed", _k_sharded_packed,
                       _c_sharded, wants_mesh=True),
        KernelContract("sharding.fit_resident_core", _k_resident,
                       _c_resident, wants_mesh=True),
    )


def _cases(matrix: KernelMatrix, mesh: bool) -> List[ShapeCase]:
    out = []
    for b in matrix.batch_sizes:
        for t in matrix.lengths:
            for n_cp in matrix.n_changepoints:
                for r in matrix.num_regressors:
                    if not mesh:
                        out.append(ShapeCase(b, t, n_cp, r))
                        continue
                    for ms in matrix.mesh_shapes:
                        # The raw kernels require divisibility (the
                        # public fit_sharded wrappers pad); the matrix
                        # checks the layouts the wrappers produce.
                        if b % ms[0] == 0 and t % ms[1] == 0:
                            out.append(ShapeCase(b, t, n_cp, r, ms))
    return out


def check_kernels(
    matrix: KernelMatrix,
    kernels: Optional[Sequence[KernelContract]] = None,
) -> List[Finding]:
    """Run every kernel contract over the shape matrix; returns findings
    (empty = all contracts hold).

    Traces run under ``jax.experimental.enable_x64``: with x64 OFF, jax
    silently truncates every f64 request to f32, so the f64-leak gate
    would be vacuous — x64 ON is the mode where an undisciplined
    dtype (a strong np.float64 scalar, a default-dtype ``random.*``
    call) actually surfaces as a float64 leaf or a carry-mismatch trace
    error instead of hiding until real hardware.  Kernels with explicit
    f32 dtypes trace identically in both modes.
    """
    import jax

    with jax.experimental.enable_x64():
        return _check_kernels(matrix, kernels)


def _check_kernels(
    matrix: KernelMatrix,
    kernels: Optional[Sequence[KernelContract]],
) -> List[Finding]:
    findings: List[Finding] = []
    for k in (default_kernels() if kernels is None else kernels):
        for case in _cases(matrix, k.wants_mesh):
            try:
                out = k.run(case)
            except Exception as e:  # a trace error IS a contract failure
                findings.append(Finding(
                    "contract-trace", f"<kernel:{k.name}>", 0, case.label,
                    f"tracing failed: {type(e).__name__}: {e}",
                ))
                continue
            if out is None:
                continue  # case not runnable here (too few devices)
            for what, leaf in _leaf_items(out):
                dt = str(getattr(leaf, "dtype", ""))
                if dt in _BANNED_DTYPES:
                    findings.append(Finding(
                        "f64-leak", f"<kernel:{k.name}>", 0, case.label,
                        f"output leaf {what} has banned dtype {dt} "
                        "(x64 drift leaked into a kernel result)",
                    ))
            for msg in k.check(case, out):
                findings.append(Finding(
                    "contract-shape", f"<kernel:{k.name}>", 0,
                    case.label, msg,
                ))
    return findings
