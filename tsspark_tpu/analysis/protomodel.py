"""Happens-before model checker for the sentinel protocols.

Every crash-safety story in this package has the same shape: a writer
lands PAYLOAD artifacts first, then an atomic GATE artifact last — the
sentinel whose presence is the unit of visibility (``fileproto``'s
ArtifactSpec lifecycles tell the story in prose).  The chaos harness
samples kill-points inside those windows at runtime; this module makes
the order itself a static gate:

1. **Declared ordering edges** — each :class:`ProtocolSpec` extends the
   ``fileproto`` registry with the write ORDER a protocol's owner must
   emit: spec-first → payload → sentinel-LAST (plane land), patch file
   before memmap scatter before the visibility record (delta land),
   plan pin before fit before publish before flip (refit cycle),
   snapshot files before the manifest (registry publish).

2. **Static order verification** — the writer's call graph is walked in
   program order (same-module callees inlined), producing the linear
   EVENT sequence of write sites (classified against the artifact
   registry, with module-constant and one-level local resolution so
   ``os.path.join(d, SNAP_OK)`` is recognizable) and call markers.  The
   declared step chain must embed into that sequence (greedy
   subsequence), and a gate's first emission must follow every payload
   it certifies — the ``hb-order`` finding is a sentinel written before
   its payload.

3. **Kill-point sweep** — a small-model enumerator walks every
   linearization the declared partial order admits and inserts a
   kill-point after each write: a prefix is SAFE iff every gate present
   certifies only payloads already present (killed-before-gate ⇒ the
   state is invisible or resumable per the step's declared reader;
   killed-after ⇒ complete).  This turns the chaos harness's sampled
   kill-points into an exhaustive static sweep over the lifecycle DAG:
   a registry edit that weakens the edges until a gate may precede its
   payload fails here (``hb-unsafe``) before any storm runs.

Findings: ``hb-order`` (writer emits events out of declared order),
``hb-missing`` (a declared step never appears in the writer's closure —
the model drifted from the code), ``hb-unsafe`` (the declared DAG
admits an unsafe prefix), ``hb-model`` (an inconsistent spec: a gate
certifying an unknown step, a payload with no reader story).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tsspark_tpu.analysis.findings import Finding
from tsspark_tpu.analysis import fileproto

#: Inlining bound for the writer call-graph walk (protocol writers are
#: shallow; the bound only guards against pathological recursion).
_MAX_DEPTH = 8


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class StepSpec:
    """One write step of a protocol lifecycle.

    ``pattern`` locates the step in the writer's extracted event
    sequence: ``art:<name>`` matches a write site classified as that
    ArtifactSpec; ``tok:<fragment>`` matches a write site whose path
    expression carries the fragment (string constant, resolved module
    constant, or the name of the path-building helper); ``call:<fn>``
    matches a call event.  ``role`` is ``payload`` / ``gate`` /
    ``advisory``; a gate's ``certifies`` names the payload steps its
    landing makes visible.  ``reader`` is the resumer that classifies a
    prefix ending at this step as invisible-or-resumable — required for
    payloads (a payload nobody knows how to tolerate mid-crash is a
    model hole, not a formality)."""

    name: str
    pattern: str
    role: str = "payload"
    certifies: Tuple[str, ...] = ()
    reader: str = ""


@dataclasses.dataclass(frozen=True)
class ProtocolSpec:
    """One protocol: an owning writer plus its ordered steps.

    ``edges`` is the declared happens-before partial order as
    ``(before, after)`` step-name pairs; empty means the full chain in
    ``steps`` order.  The static verification checks the writer's real
    emission order embeds the chain; the kill-point sweep checks every
    linearization the edges admit."""

    name: str
    writer_module: str   # repo-relative path
    writer_root: str     # qualname of the function whose closure writes
    steps: Tuple[StepSpec, ...]
    edges: Tuple[Tuple[str, str], ...] = ()
    resume: str = ""

    def edge_pairs(self) -> Tuple[Tuple[str, str], ...]:
        if self.edges:
            return self.edges
        names = [s.name for s in self.steps]
        return tuple(zip(names[:-1], names[1:]))


PROTOCOLS: Tuple[ProtocolSpec, ...] = (
    ProtocolSpec(
        "plane-base-land",
        "tsspark_tpu/data/plane.py", "write_shard",
        steps=(
            StepSpec("spec", "art:plane-spec",
                     reader="ready_coverage ignores dirs without "
                            "spec.json; create_columns re-lands it"),
            StepSpec("scatter", "call:open_memmap",
                     reader="readers trust only sentinel-covered rows; "
                            "unsentineled column bytes are invisible"),
            StepSpec("sentinel", "call:write_sentinel", role="gate",
                     certifies=("spec", "scatter")),
        ),
        resume="a producer killed mid-shard leaves no sentinel; any "
               "successor regenerates the block-seeded rows bitwise "
               "and re-lands",
    ),
    ProtocolSpec(
        "plane-delta-land",
        "tsspark_tpu/data/plane.py", "land_delta",
        steps=(
            StepSpec("patch", "tok:_delta_patch_path",
                     reader="a patch without its deltaok record is "
                            "never unioned by advanced_since; "
                            "write_shard replays only visible deltas"),
            StepSpec("scatter", "call:_apply_patch",
                     reader="absolute-value scatter is bitwise "
                            "idempotent; repair rolls a torn shard "
                            "back to base + visible patches"),
            StepSpec("reland", "call:_reland_sentinel_from_disk",
                     reader="re-landed sentinel carries post-delta "
                            "CRCs; a kill before it reads as shard "
                            "corruption and repair() re-lands"),
            StepSpec("ok", "tok:_delta_ok_path", role="gate",
                     certifies=("patch", "scatter", "reland")),
        ),
        resume="advanced_since unions only deltaok_ records, so a "
               "lander killed anywhere earlier leaves the delta "
               "invisible; the flock serializes racing landers",
    ),
    ProtocolSpec(
        # The ONE generic plane publish every implementation routes
        # through (data plane base shards, snapshot planes, delta
        # copy-forwards): verifying this writer verifies them all.
        "plane-protocol",
        "tsspark_tpu/plane/protocol.py", "publish_plane",
        steps=(
            StepSpec("spec", "call:write_spec",
                     reader="readers require spec + sentinel; a "
                            "spec-only dir is rejected whole"),
            StepSpec("columns", "call:write_column",
                     reader="columns are invisible until the CRC "
                            "sentinel lands; readers reject mismatches "
                            "and fall back down the version chain"),
            StepSpec("sentinel", "call:write_sentinel", role="gate",
                     certifies=("spec", "columns")),
        ),
        resume="a publisher killed mid-plane leaves no sentinel: the "
               "plane reads as absent/in-progress and any successor "
               "republishes the same bytes",
    ),
    ProtocolSpec(
        "snap-plane-delta",
        "tsspark_tpu/serve/snapplane.py", "write_plane_delta",
        steps=(
            StepSpec("spec", "call:write_spec",
                     reader="same attach() gate as the full plane"),
            StepSpec("columns", "call:write_column",
                     reader="hardlinked or copy-forwarded columns are "
                            "invisible until the sentinel lands"),
            StepSpec("sentinel", "call:write_sentinel", role="gate",
                     certifies=("spec", "columns")),
            StepSpec("delta-manifest", "tok:DELTA_MANIFEST",
                     role="advisory",
                     reader="pure metadata: the registry manifest "
                            "referencing the dir is the visibility "
                            "gate; carry-forward degrades to a full "
                            "cache drop when it is absent"),
        ),
        resume="orphan version dirs are skipped by the allocator; the "
               "registry manifest is the real flip",
    ),
    ProtocolSpec(
        "forecast-plane",
        "tsspark_tpu/serve/fplane.py", "write_plane",
        steps=(
            StepSpec("spec", "call:write_spec",
                     reader="attach() requires spec + sentinel; a "
                            "spec-only dir raises corrupt and the "
                            "engine keeps its compute path"),
            StepSpec("columns", "call:write_column",
                     reader="forecast columns are invisible until the "
                            "CRC sentinel lands; the fplane_publish "
                            "fault point tears here and attach() "
                            "rejects the plane whole"),
            StepSpec("sentinel", "call:write_sentinel", role="gate",
                     certifies=("spec", "columns")),
        ),
        resume="a publisher killed mid-plane leaves no fplaneok.json: "
               "the version serves through the compute path (bitwise "
               "the same numbers) and any successor's maybe_publish "
               "re-lands identical bytes",
    ),
    ProtocolSpec(
        "forecast-plane-delta",
        "tsspark_tpu/serve/fplane.py", "write_plane_delta",
        steps=(
            StepSpec("spec", "call:write_spec",
                     reader="same attach() gate as the full plane"),
            StepSpec("columns", "call:write_column",
                     reader="hardlinked or scatter-patched columns are "
                            "invisible until the recomputed-CRC "
                            "sentinel lands"),
            StepSpec("sentinel", "call:write_sentinel", role="gate",
                     certifies=("spec", "columns")),
        ),
        resume="the base version's plane is never touched; a torn "
               "delta plane reads as absent/corrupt for the NEW "
               "version only and the compute path covers it",
    ),
    ProtocolSpec(
        "quantile-plane",
        "tsspark_tpu/uncertainty/qplane.py", "write_qplane",
        steps=(
            StepSpec("spec", "call:write_spec",
                     reader="attach() requires spec + sentinel; a "
                            "spec-only dir raises corrupt and interval "
                            "reads stay on the sampled compute path"),
            StepSpec("columns", "call:write_column",
                     reader="quantile columns are invisible until the "
                            "CRC sentinel lands; the qplane_publish "
                            "fault point tears here and attach() "
                            "rejects the plane whole"),
            StepSpec("sentinel", "call:write_sentinel", role="gate",
                     certifies=("spec", "columns")),
        ),
        resume="a publisher killed mid-plane leaves no qplaneok.json: "
               "intervals serve through the row-local compute path "
               "(bitwise the same numbers, by the shared-sampler "
               "construction) and any successor's maybe_publish "
               "re-lands identical bytes",
    ),
    ProtocolSpec(
        "quantile-plane-delta",
        "tsspark_tpu/uncertainty/qplane.py", "write_qplane_delta",
        steps=(
            StepSpec("spec", "call:write_spec",
                     reader="same attach() gate as the full quantile "
                            "plane; the delta inherits the base spec's "
                            "sampling identity so a mixed-identity "
                            "plane cannot exist"),
            StepSpec("columns", "call:write_column",
                     reader="hardlinked or re-sampled columns are "
                            "invisible until the recomputed-CRC "
                            "sentinel lands"),
            StepSpec("sentinel", "call:write_sentinel", role="gate",
                     certifies=("spec", "columns")),
        ),
        resume="the base version's quantile plane is never touched; a "
               "torn delta reads as absent/corrupt for the NEW version "
               "only and the compute fallback covers it",
    ),
    ProtocolSpec(
        "registry-publish",
        "tsspark_tpu/serve/registry.py", "ParamRegistry.publish",
        steps=(
            StepSpec("snapshot", "call:save_state",
                     reader="an unreferenced version dir is invisible "
                            "to load(); sweep_stale_temps bounds the "
                            "orphans"),
            StepSpec("plane", "call:write_plane",
                     reader="same: publisher-private until referenced"),
            StepSpec("manifest", "art:registry-manifest", role="gate",
                     certifies=("snapshot", "plane")),
        ),
        resume="readers see the old or the new manifest, never a "
               "dangling reference: the manifest is replaced atomically "
               "AFTER the snapshot files land",
    ),
    ProtocolSpec(
        "registry-delta-publish",
        "tsspark_tpu/serve/registry.py", "ParamRegistry.publish_delta",
        steps=(
            StepSpec("plane", "call:write_plane_delta",
                     reader="publisher-private until the manifest "
                            "references the version dir"),
            StepSpec("manifest", "art:registry-manifest", role="gate",
                     certifies=("plane",)),
        ),
        resume="a publisher killed mid-delta leaves an orphan vdir; "
               "the refit plan stays pinned and the successor "
               "re-publishes",
    ),
    ProtocolSpec(
        "refit-cycle",
        "tsspark_tpu/refit.py", "run_refit",
        steps=(
            StepSpec("pin", "art:refit-plan",
                     reader="resolve_plan resumes the pinned plan on "
                            "any successor — the pin is what stops a "
                            "fresh detect racing deltas landed after "
                            "a kill"),
            StepSpec("fit", "call:fit_changed",
                     reader="chunk flushes land under leases in the "
                            "cycle dir; a resumed cycle re-claims only "
                            "missing coverage"),
            StepSpec("publish", "call:publish_delta",
                     reader="registry-delta-publish protocol: orphan "
                            "vdir until the manifest lands"),
            StepSpec("flip", "call:activate",
                     reader="publish_plan routes pool.activate / "
                            "flip_fn / registry.activate after the "
                            "publish; a kill between publish and flip "
                            "resumes via the published-base branch of "
                            "resolve_plan"),
            StepSpec("complete", "art:refit-plan", role="gate",
                     certifies=("pin", "fit", "publish", "flip")),
        ),
        resume="the plan file is the cycle's record: complete=false "
               "resumes, complete=true lets the reaper collect the "
               "cycle dir",
    ),
    ProtocolSpec(
        "alert-record",
        "tsspark_tpu/alerts/stream.py", "AlertStream.score_seq",
        steps=(
            StepSpec("spec", "call:write_spec",
                     reader="the alert log's identity lands before any "
                            "record; record_ok never consults it for "
                            "validity, so a duplicate ensure is a "
                            "no-op"),
            StepSpec("record", "art:alert-record",
                     reader="canonical record bytes are UNSCORED until "
                            "the CRC sentinel certifies them "
                            "(record_ok); the alert_publish fault "
                            "point tears here and the successor's "
                            "re-score converges bitwise"),
            StepSpec("sentinel", "call:write_sentinel", role="gate",
                     certifies=("spec", "record")),
        ),
        resume="a scorer killed at any step leaves seq without a valid "
               "sentinel: poll() re-scores it (deterministic, bitwise "
               "the original) and the delivery watermark — advanced "
               "only after sink ack — plus keyed dedup make the "
               "redelivery exactly-once",
    ),
)


# ---------------------------------------------------------------------------
# event extraction (program-order write/call sequence of a writer)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Event:
    kind: str                 # "write" | "call"
    name: str                 # artifact spec name or callee name
    tokens: Tuple[str, ...]   # path tokens for writes
    line: int


class _ModuleIndex:
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.tree = ast.parse(source, filename=relpath)
        self.consts = fileproto.module_str_constants(self.tree)
        self.functions: Dict[str, ast.AST] = {}
        self._build()

    def _build(self) -> None:
        qualnames = fileproto._fn_qualname_map(self.tree)

        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    self.functions[qualnames[id(child)]] = child
                visit(child)

        visit(self.tree)

    def resolve(self, callee: str,
                caller_qual: str) -> Optional[Tuple[str, ast.AST]]:
        """Same-module function for a simple callee name: a sibling
        method of the caller's class first, then a module-level def."""
        if "." in caller_qual:
            cls_prefix = caller_qual.rsplit(".", 1)[0]
            qual = f"{cls_prefix}.{callee}"
            if qual in self.functions:
                return qual, self.functions[qual]
        if callee in self.functions:
            return callee, self.functions[callee]
        return None


def _callee_name(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _path_tokens(node: ast.AST, consts: Dict[str, str],
                 local_map: Dict[str, ast.AST]) -> Tuple[str, ...]:
    """Tokens identifying a write site's target path: string constants,
    resolved module constants, referenced constant NAMES, and the names
    of path-building helper calls — with one level of local-variable
    substitution (``dst = _col_path(...); atomic_write(dst, ...)``)."""
    toks: List[str] = []

    def walk(n: ast.AST, depth: int) -> None:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                            str):
                toks.append(sub.value)
            elif isinstance(sub, ast.Name):
                if sub.id in consts:
                    toks.append(sub.id)
                    toks.append(consts[sub.id])
                elif depth == 0 and sub.id in local_map:
                    walk(local_map[sub.id], 1)
            elif isinstance(sub, ast.Call):
                name = _callee_name(sub)
                if name:
                    toks.append(name)

    walk(node, 0)
    return tuple(toks)


def _write_event(call: ast.Call, qual: str,
                 consts: Dict[str, str],
                 local_map: Dict[str, ast.AST]) -> Optional[Event]:
    """An Event for a write-site call (open-for-write / np.save / dump /
    atomic_write), classified against the artifact registry."""
    func = call.func
    target: Optional[ast.AST] = None
    if isinstance(func, ast.Name) and func.id in fileproto._ATOMIC_FNS:
        target = call.args[0] if call.args else None
    elif isinstance(func, ast.Name) and func.id == "open":
        mode = ""
        if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
            mode = str(call.args[1].value)
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = str(kw.value.value)
        if not any(c in mode for c in "wax+"):
            return None
        target = call.args[0] if call.args else None
    elif isinstance(func, ast.Attribute) \
            and func.attr in fileproto._WRITE_FNS and call.args:
        target = (call.args[1] if func.attr == "dump"
                  and len(call.args) > 1 else call.args[0])
    if target is None:
        return None
    tokens = _path_tokens(target, consts, local_map)
    site = fileproto.WriteSite(
        "", call.lineno, qual, "w",
        tuple(t for t in tokens), False, False,
    )
    spec = fileproto._classify(site)
    return Event("write", spec.name if spec else "?", tokens,
                 call.lineno)


def extract_events(index: _ModuleIndex, root_qual: str) -> List[Event]:
    """The writer's program-order event sequence, same-module callees
    inlined (depth-capped, cycle-guarded)."""
    events: List[Event] = []

    def local_assigns(fn: ast.AST) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                out[sub.targets[0].id] = sub.value
        return out

    def walk_fn(qual: str, fn: ast.AST, depth: int,
                stack: Tuple[str, ...]) -> None:
        locals_map = local_assigns(fn)

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and node is not fn:
                return  # nested defs run when called, not here
            if isinstance(node, ast.Call):
                # Arguments evaluate before the call itself.
                for child in ast.iter_child_nodes(node):
                    visit(child)
                ev = _write_event(node, qual, index.consts, locals_map)
                if ev is not None:
                    events.append(ev)
                callee = _callee_name(node)
                if callee:
                    events.append(Event("call", callee, (),
                                        node.lineno))
                    resolved = index.resolve(callee, qual)
                    if (resolved is not None and depth < _MAX_DEPTH
                            and resolved[0] not in stack):
                        walk_fn(resolved[0], resolved[1], depth + 1,
                                stack + (resolved[0],))
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)

    fn = index.functions.get(root_qual)
    if fn is None:
        return events
    walk_fn(root_qual, fn, 0, (root_qual,))
    return events


def _matches(event: Event, pattern: str) -> bool:
    kind, _, arg = pattern.partition(":")
    if kind == "art":
        return event.kind == "write" and event.name == arg
    if kind == "tok":
        return event.kind == "write" and any(
            arg in t or t == arg for t in event.tokens
        )
    if kind == "call":
        return event.kind == "call" and event.name == arg
    return False


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------


def _check_model(proto: ProtocolSpec,
                 findings: List[Finding]) -> bool:
    """Internal consistency of one spec; False stops further checks."""
    names = [s.name for s in proto.steps]
    ok = True

    def emit(msg: str) -> None:
        findings.append(Finding(
            "hb-model", proto.writer_module, 0, proto.writer_root,
            f"protocol {proto.name}: {msg}",
        ))

    if len(set(names)) != len(names):
        emit("duplicate step names")
        ok = False
    for s in proto.steps:
        if s.role not in ("payload", "gate", "advisory"):
            emit(f"step {s.name} has unknown role {s.role!r}")
            ok = False
        if s.role == "gate" and not s.certifies:
            emit(f"gate {s.name} certifies nothing — a gate that "
                 "gates nothing is a payload mislabeled as a sentinel")
            ok = False
        for c in s.certifies:
            if c not in names:
                emit(f"gate {s.name} certifies unknown step {c!r}")
                ok = False
        if s.role == "payload" and not s.reader.strip():
            emit(f"payload step {s.name} declares no reader/resumer "
                 "story — who tolerates a crash right after it?")
            ok = False
    for a, b in proto.edge_pairs():
        if a not in names or b not in names:
            emit(f"edge ({a!r}, {b!r}) names an unknown step")
            ok = False
    return ok


def _check_writer_order(proto: ProtocolSpec, root: str,
                        findings: List[Finding]) -> None:
    path = os.path.join(root, proto.writer_module)
    if not os.path.exists(path):
        findings.append(Finding(
            "hb-missing", proto.writer_module, 0, proto.writer_root,
            f"protocol {proto.name}: writer module is gone — delete "
            "or update the ProtocolSpec",
        ))
        return
    with open(path, "r") as fh:
        source = fh.read()
    index = _ModuleIndex(proto.writer_module, source)
    if proto.writer_root not in index.functions:
        findings.append(Finding(
            "hb-missing", proto.writer_module, 0, proto.writer_root,
            f"protocol {proto.name}: writer {proto.writer_root} not "
            "found — the model drifted from the code",
        ))
        return
    events = extract_events(index, proto.writer_root)
    # Greedy subsequence embedding of the declared chain.
    pos = 0
    matched: Dict[str, int] = {}
    for step in proto.steps:
        found = None
        for i in range(pos, len(events)):
            if _matches(events[i], step.pattern):
                found = i
                break
        if found is None:
            # Distinguish "never emitted at all" (model drift) from
            # "emitted, but before an earlier step" (order violation).
            anywhere = any(_matches(e, step.pattern) for e in events)
            rule = "hb-order" if anywhere else "hb-missing"
            line = next((e.line for e in events
                         if _matches(e, step.pattern)), 0)
            findings.append(Finding(
                rule, proto.writer_module, line, proto.writer_root,
                f"protocol {proto.name}: step {step.name!r} "
                f"({step.pattern}) "
                + ("is emitted BEFORE its declared predecessor — the "
                   "sentinel order the crash story depends on is "
                   "violated" if anywhere else
                   "never appears in the writer's call graph — update "
                   "the model or the writer"),
            ))
            return
        matched[step.name] = found
        pos = found + 1
    # A gate must not have an occurrence earlier than a certified
    # payload's matched position (only meaningful when the gate's
    # pattern is unique among the declared steps).
    for step in proto.steps:
        if step.role != "gate":
            continue
        shared = any(s.pattern == step.pattern and s.name != step.name
                     for s in proto.steps)
        if shared:
            continue
        first = next((i for i, e in enumerate(events)
                      if _matches(e, step.pattern)), None)
        for c in step.certifies:
            if first is not None and first < matched[c]:
                findings.append(Finding(
                    "hb-order", proto.writer_module,
                    events[first].line, proto.writer_root,
                    f"protocol {proto.name}: gate {step.name!r} is "
                    f"first written before payload {c!r} — a reader "
                    "observing the gate would trust payload bytes "
                    "that may not exist yet",
                ))


def _linearizations(
    names: Sequence[str],
    edges: Sequence[Tuple[str, str]],
    cap: int = 2048,
) -> Tuple[List[Tuple[str, ...]], bool]:
    """(orders, truncated): all topological orders the partial order
    admits, up to ``cap``.  ``truncated`` True means the enumeration
    was cut — the caller must surface that loudly, or the 'exhaustive'
    sweep silently degrades to a sample."""
    out: List[Tuple[str, ...]] = []
    truncated = False
    after: Dict[str, Set[str]] = {n: set() for n in names}
    for a, b in edges:
        after[b].add(a)

    def rec(placed: Tuple[str, ...], remaining: Set[str]) -> None:
        nonlocal truncated
        if len(out) >= cap:
            truncated = True
            return
        if not remaining:
            out.append(placed)
            return
        for n in sorted(remaining):
            if after[n] <= set(placed):
                rec(placed + (n,), remaining - {n})

    rec((), set(names))
    return out, truncated


def _check_killpoints(proto: ProtocolSpec,
                      findings: List[Finding]) -> None:
    """Exhaustive kill-point sweep over the declared lifecycle DAG."""
    names = [s.name for s in proto.steps]
    by_name = {s.name: s for s in proto.steps}
    orders, truncated = _linearizations(names, proto.edge_pairs())
    if truncated:
        findings.append(Finding(
            "hb-model", proto.writer_module, 0, proto.writer_root,
            f"protocol {proto.name}: the declared edges admit more "
            "linearizations than the sweep cap — the kill-point sweep "
            "is no longer exhaustive; add ordering edges (a protocol "
            "this unconstrained has no crash story anyway)",
        ))
        return
    for order in orders:
        for k in range(len(order) + 1):
            prefix = set(order[:k])
            for g in prefix:
                step = by_name[g]
                if step.role != "gate":
                    continue
                missing = [c for c in step.certifies
                           if c not in prefix]
                if missing:
                    findings.append(Finding(
                        "hb-unsafe", proto.writer_module, 0,
                        proto.writer_root,
                        f"protocol {proto.name}: the declared edges "
                        f"admit order {order} — killed after "
                        f"{g!r} lands, payload(s) {missing} are "
                        "missing while the gate says they are "
                        "visible; add the ordering edge(s)",
                    ))
                    return  # one counterexample per protocol is enough


def check_protocols(root: str,
                    protocols: Sequence[ProtocolSpec] = PROTOCOLS
                    ) -> List[Finding]:
    findings: List[Finding] = []
    for proto in protocols:
        if _check_model(proto, findings):
            _check_writer_order(proto, root, findings)
            _check_killpoints(proto, findings)
    return findings
