"""Effect-inference gate: per-path effect budgets over the call graph.

The repo's open speed tentpole (the materialized forecast plane) is
defined by an *effect* claim — a hot point-forecast read must reach the
memmap with **zero JAX dispatch, zero compile, zero durable write** —
and value-level tests cannot state a claim of that shape.  This checker
can: it infers, bottom-up over the same qualified-import call graph the
trace lint walks, the set of side effects every package function can
*transitively* reach, then checks declared per-path budgets from the
committed ``[tool.tsspark.analysis.effects]`` pyproject table.

The effect lattice (a flat powerset — effects union up the call graph):

* ``jax-dispatch``  — any ``jnp``/``jax``/``lax`` op call, a call into
  a jit-decorated package function, ``.block_until_ready()``.
* ``jax-compile``   — a trace entry: ``jax.jit``/``pjit``/
  ``eval_shape``/``make_jaxpr``, or calling a jit-decorated function
  (its first dispatch compiles).
* ``durable-write`` — the storage fault domain's sanctioned writers
  (``tsspark_tpu.io``: ``atomic_write``/``atomic_write_text``/
  ``append_line``/``hardlink``/``link_or_copy``/``fsync_dir``, plus
  ``utils.atomic``).  Raw writes *inside* those choke modules count as
  durable, not raw — they ARE the choke point.
* ``raw-fs-write``  — ``open(..., "w"/"a"/"x"/"+")``, ``os.replace``/
  ``rename``/``link``/``write``/``makedirs``/``unlink``/... ,
  ``np.save*``, ``json.dump``/``pickle.dump``, ``shutil`` copies.
* ``spawn``         — ``subprocess.Popen``/``run``/``check_*``,
  ``os.fork``/``exec*``/``posix_spawn``.
* ``lock-acquire``  — ``with <something lock-ish>:`` / ``.acquire()``.
* ``blocking-io``   — ``time.sleep``, ``select.select``, socket
  ``recv``/``accept``/``connect``/``sendall``, ``.wait(...)``.
* ``env-read``      — ``os.environ`` reads / ``os.getenv``.
* ``fault-point``   — ``resilience.faults.inject`` sites (the chaos
  harness's armable kill points).

Budgets are **path** claims: each entry names root functions
(``relpath::qualname``), the effects the path must never reach, and
optional ``allow_via`` cut points — declared escape hatches (the idle
tick's spill prefetch, its stranded-probe re-publish) whose own effects
are deliberate and reviewed.  A finding is anchored at the OFFENDING
function's evidence line (where an inline ``# lint-ok[effect-budget]:``
waiver can sit next to the actual effect), and its message carries the
full call chain from the root, so "how does the respond path reach a
durable write?" is answered by the gate output itself.

Precision notes (heuristic BY DESIGN, like every pass here): qualified
imports join precisely; attribute/simple calls resolve nested defs
first, then same-class siblings, then same-module functions, and only
then fall back to a package-wide name join — so ``start_watch``'s
nested ``loop`` never inherits the effects of ``engine.start``'s
``loop``.  External modules (numpy, jax — beyond the jax effect
classification itself) contribute no edges.

The env-var contract sub-checker rides the same scan: every
``TSSPARK_*`` read (string literal, module constant, or imported
constant like ``faults.ENV_VAR``) must be registered in the committed
``EnvSpec`` table (owner module + child-propagation rule), and every
spawn site that passes ``env=`` must hand children an environment
provably seeded from ``os.environ`` (``dict(os.environ)``, a recognized
builder like ``orchestrate._child_env``) — otherwise specs marked
``inherit`` (``TSSPARK_FAULTS``, ``TSSPARK_DISK_BUDGET_*``,
``TSSPARK_TRACE``, ...) would silently stop reaching workers, exactly
the convention-not-contract gap this table closes.

Rules: ``effect-budget``, ``env-unregistered``, ``env-propagation``,
``env-unused``, ``fault-scope``, ``effect-model`` (budget/table
entries that no longer match the tree — a stale declaration checks
nothing and must die).  All honor the inline waiver and the pyproject
baseline; docs/ANALYSIS.md section 6 is the operator guide.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tsspark_tpu.analysis.findings import Finding
from tsspark_tpu.analysis.tracelint import (
    _ModuleScan,
    _jit_call_of,
    _walk_functions,
)

EFFECTS: Tuple[str, ...] = (
    "jax-dispatch", "jax-compile", "durable-write", "raw-fs-write",
    "spawn", "lock-acquire", "blocking-io", "env-read", "fault-point",
)

#: The storage fault domain's choke modules: raw writes INSIDE them are
#: the sanctioned durable implementation, not a bypass.
_DURABLE_CHOKE_RELPATHS = (
    "tsspark_tpu/io/durable.py",
    "tsspark_tpu/utils/atomic.py",
)
_DURABLE_MODULE_PREFIXES = (
    "tsspark_tpu.io", "tsspark_tpu.utils.atomic",
)
_DURABLE_FNS = {
    "atomic_write", "atomic_write_text", "append_line", "hardlink",
    "link_or_copy", "fsync_dir", "open_memmap",
}
_RAW_OS_FNS = {
    "replace", "rename", "link", "symlink", "write", "truncate",
    "makedirs", "mkdir", "unlink", "remove", "rmdir", "removedirs",
}
_OS_SPAWN_FNS = {"fork", "execv", "execve", "execvp", "posix_spawn",
                 "spawnv", "spawnl"}
_SUBPROCESS_FNS = {"Popen", "run", "call", "check_call", "check_output"}
_SHUTIL_WRITE_FNS = {"copy", "copy2", "copyfile", "copytree", "move",
                     "rmtree"}
_NP_SAVE_FNS = {"save", "savez", "savez_compressed"}
_JAX_COMPILE_ATTRS = {"jit", "pjit", "eval_shape", "make_jaxpr",
                      "xla_computation"}
_BLOCKING_METHODS = {"recv", "recvfrom", "accept", "connect", "sendall",
                     "wait"}
#: Builtins whose simple-name call must NOT join a package function of
#: the same name (``open(path)`` joining ``ParamRegistry.open`` would
#: hand every reader the registry's write effects).
_BUILTIN_SHADOW = {"open", "print", "sorted", "iter", "next", "super",
                   "min", "max", "abs", "round", "sum", "repr", "vars"}


# ---------------------------------------------------------------------------
# committed configuration: [tool.tsspark.analysis.effects]
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class EnvSpec:
    """One registered ``TSSPARK_*`` variable: which module owns the
    read, and whether spawned children must inherit it."""

    var: str
    owner: str       # repo-relative module path that reads it
    inherit: bool    # True: every spawn site must forward it


@dataclasses.dataclass(frozen=True)
class PathBudget:
    """One per-path effect claim: from each root, no function whose
    base effects intersect ``forbid`` may be reachable, except through
    the declared ``allow_via`` cut points."""

    name: str
    roots: Tuple[str, ...]       # "relpath::qualname"
    forbid: Tuple[str, ...]      # effect names from EFFECTS
    allow_via: Tuple[str, ...] = ()  # "relpath::qualname" cut points


@dataclasses.dataclass(frozen=True)
class EffectsConfig:
    paths: Tuple[PathBudget, ...] = ()
    env: Tuple[EnvSpec, ...] = ()
    fault_modules: Tuple[str, ...] = ()


def _parse_ref(ref: str, where: str) -> Tuple[str, str]:
    try:
        relpath, qualname = ref.split("::", 1)
    except ValueError:
        raise ValueError(
            f"effects config {where}: {ref!r} is not "
            "'<relpath>::<qualname>'"
        )
    return relpath.strip(), qualname.strip()


def load_config(root: Optional[str] = None) -> EffectsConfig:
    """``EffectsConfig`` from ``<root>/pyproject.toml``'s
    ``[tool.tsspark.analysis.effects]`` table (empty config when the
    file or table is absent).  Unknown effect names and malformed
    entries raise at load — a typo'd budget silently checking nothing
    would pass vacuously, the same policy as the suppression parser."""
    from tsspark_tpu.analysis.config import _load_toml, repo_root

    root = root or repo_root()
    path = os.path.join(root, "pyproject.toml")
    if not os.path.exists(path):
        return EffectsConfig()
    block = (
        _load_toml(path).get("tool", {}).get("tsspark", {})
        .get("analysis", {}).get("effects", {})
    )
    paths = []
    for entry in block.get("paths", ()):
        name = entry.get("name")
        if not name:
            raise ValueError("effects path budget without a 'name'")
        for eff in entry.get("forbid", ()):
            if eff not in EFFECTS:
                raise ValueError(
                    f"effects budget {name!r} forbids unknown effect "
                    f"{eff!r} (known: {', '.join(EFFECTS)})"
                )
        roots = tuple(entry.get("roots", ()))
        if not roots:
            raise ValueError(f"effects budget {name!r} declares no roots")
        for ref in roots + tuple(entry.get("allow_via", ())):
            _parse_ref(ref, f"budget {name!r}")
        paths.append(PathBudget(
            name=str(name), roots=roots,
            forbid=tuple(entry.get("forbid", ())),
            allow_via=tuple(entry.get("allow_via", ())),
        ))
    env = []
    for entry in block.get("env", ()):
        var = entry.get("var")
        if not var or not str(var).startswith("TSSPARK_"):
            raise ValueError(
                f"EnvSpec var {var!r} must be a TSSPARK_* name"
            )
        if "owner" not in entry or "inherit" not in entry:
            raise ValueError(
                f"EnvSpec {var!r} needs 'owner' and 'inherit' — an "
                "unowned variable has no propagation story to check"
            )
        env.append(EnvSpec(var=str(var), owner=str(entry["owner"]),
                           inherit=bool(entry["inherit"])))
    return EffectsConfig(
        paths=tuple(paths), env=tuple(env),
        fault_modules=tuple(block.get("fault_modules", ())),
    )


# ---------------------------------------------------------------------------
# package scan: functions, call edges, base effects
# ---------------------------------------------------------------------------

def _dotted(relpath: str) -> str:
    mod = relpath.replace(os.sep, "/")
    mod = mod[:-3] if mod.endswith(".py") else mod
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def _binding(scan: _ModuleScan, name: str) -> Optional[str]:
    """Dotted target a local name is bound to by imports, else None.
    ``import jax.numpy as jnp`` -> ``jax.numpy``; ``from
    tsspark_tpu.resilience import faults`` ->
    ``tsspark_tpu.resilience.faults``."""
    if name in scan.imports:
        return scan.imports[name]
    if name in scan.from_imports:
        mod, orig = scan.from_imports[name]
        return f"{mod}.{orig}" if mod else orig
    return None


def _is_os_environ(scan: _ModuleScan, node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and _binding(scan, node.value.id) == "os")


def _lockish_with_item(scan: _ModuleScan, ctx: ast.AST) -> bool:
    """Does a ``with`` context expression look like a lock?  Name/attr
    containing "lock"/"mutex", or a call to one (``self._locked()``)."""
    if isinstance(ctx, ast.Call):
        ctx = ctx.func
    name = None
    if isinstance(ctx, ast.Attribute):
        name = ctx.attr
    elif isinstance(ctx, ast.Name):
        name = ctx.id
    return bool(name) and ("lock" in name.lower() or "mutex" in name.lower())


def _open_mode_writes(call: ast.Call) -> bool:
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and any(c in mode for c in "wax+")


class _EffectGraph:
    """Every package function, its outgoing call edges (resolved with
    nested -> class -> module -> package preference), and its BASE
    effects with one evidence (line, detail) per effect."""

    def __init__(self, scans: List[_ModuleScan]):
        self.scans = scans
        self.scan_of: Dict[str, _ModuleScan] = {
            s.relpath: s for s in scans
        }
        self.by_dotted: Dict[str, _ModuleScan] = {
            _dotted(s.relpath): s for s in scans
        }
        self.info_of = {
            (s.relpath, qual): info
            for s in scans for qual, info in s.functions.items()
        }
        self.by_name: Dict[str, List[Tuple[str, str]]] = {}
        for s in scans:
            for qual in s.functions:
                self.by_name.setdefault(
                    qual.rsplit(".", 1)[-1], []
                ).append((s.relpath, qual))
        self.constants: Dict[str, Dict[str, str]] = {
            s.relpath: _module_str_constants(s) for s in scans
        }
        self.base: Dict[Tuple[str, str], Dict[str, Tuple[int, str]]] = {}
        for key, info in self.info_of.items():
            self.base[key] = _base_effects(
                self.scan_of[key[0]], info,
                durable_choke=key[0].replace(os.sep, "/")
                in _DURABLE_CHOKE_RELPATHS,
            )
        self.succ: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {
            key: self._successors(key) for key in self.info_of
        }

    def _resolve_simple(self, key: Tuple[str, str],
                        name: str) -> List[Tuple[str, str]]:
        if name in _BUILTIN_SHADOW:
            return []
        relpath, qual = key
        scan = self.scan_of[relpath]
        # 1. nested def of this very function.
        nested = [q for q in scan.functions
                  if q.startswith(qual + ".")
                  and q.rsplit(".", 1)[-1] == name]
        if nested:
            return [(relpath, q) for q in nested]
        # 2. sibling in the same class (self._claim_slot()).
        if "." in qual:
            prefix = qual.rsplit(".", 1)[0]
            sib = f"{prefix}.{name}"
            if sib in scan.functions:
                return [(relpath, sib)]
        # 3. any definition in the same module.
        local = [q for q in scan.functions
                 if q == name or q.endswith("." + name)]
        if local:
            return [(relpath, q) for q in local]
        # 4. package-wide simple-name join (the tracelint fallback).
        return list(self.by_name.get(name, ()))

    def _resolve_qual(self, mod: str, name: str,
                      depth: int = 0) -> List[Tuple[str, str]]:
        scan = self.by_dotted.get(mod)
        if scan is not None:
            hits = [q for q in scan.functions
                    if q == name or q.endswith("." + name)]
            if hits:
                return [(scan.relpath, q) for q in hits]
            # A re-export: the module (typically a package __init__)
            # imports the name from somewhere else — follow it there
            # PRECISELY rather than joining every same-named function
            # (``obs.record`` must reach obs.context.record, not
            # ChunkAutotuner.record).  Depth-bounded against import
            # cycles.
            if depth < 4:
                if name in scan.from_imports:
                    fmod, forig = scan.from_imports[name]
                    target = f"{fmod}.{forig}" if fmod else forig
                    if target in self.by_dotted:
                        return []   # imported a MODULE, called? drop
                    return self._resolve_qual(fmod, forig, depth + 1)
                if name in scan.imports:
                    return []       # the attr is a module, not a call
        internal = mod in self.by_dotted or any(
            d.startswith(mod + ".") for d in self.by_dotted
        )
        # A scanned package whose __init__ dynamically exposes the
        # name: fall back to the name join rather than dropping the
        # edge.
        return list(self.by_name.get(name, ())) if internal else []

    def _edges(self, scan: _ModuleScan, info) -> Tuple[Set[str],
                                                       Set[Tuple[str,
                                                                 str]]]:
        """Own edge extraction (richer than ``_FnInfo.calls``): a call
        through a FROM-imported module (``from serve import snapplane;
        snapplane.attach(...)``) resolves as a qualified edge into that
        module instead of degrading to a package-wide simple-name join
        — tracelint can afford that imprecision, a budget checker
        cannot."""
        from tsspark_tpu.analysis.tracelint import _GENERIC_METHODS

        simple: Set[str] = set()
        qual: Set[Tuple[str, str]] = set()
        local_names: Set[str] = set(info.param_names)
        nested: Set[ast.AST] = set()
        for sub in ast.walk(info.node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not info.node:
                nested.update(ast.walk(sub))
            if isinstance(sub, ast.Name) \
                    and isinstance(sub.ctx, ast.Store):
                local_names.add(sub.id)
        for sub in ast.walk(info.node):
            if not isinstance(sub, ast.Call) or sub in nested:
                continue
            f = sub.func
            if isinstance(f, ast.Name):
                if f.id in scan.from_imports:
                    qual.add(scan.from_imports[f.id])
                else:
                    simple.add(f.id)
            elif isinstance(f, ast.Attribute) \
                    and f.attr not in _GENERIC_METHODS:
                recv = f.value
                if isinstance(recv, ast.Name):
                    b = _binding(scan, recv.id)
                    if b is not None:
                        qual.add((b, f.attr))
                    elif not isinstance(recv.ctx, ast.Store):
                        simple.add(f.attr)
                elif isinstance(recv, ast.Subscript):
                    pass   # x.at[i].set(v) — never a package module
                else:
                    simple.add(f.attr)
            # Function references passed as arguments (thread targets,
            # callbacks) run on this function's behalf.
            for a in list(sub.args) + [kw.value for kw in sub.keywords]:
                if isinstance(a, ast.Name):
                    if a.id in scan.from_imports:
                        qual.add(scan.from_imports[a.id])
                    elif a.id not in local_names:
                        simple.add(a.id)
        return simple, qual

    def _successors(self, key: Tuple[str, str]) -> Set[Tuple[str, str]]:
        # ``faults.inject`` is an effect SINK: the fault actions it can
        # reach (lost-fsync replay, simulated crashes) model the
        # FAILURE of the caller's own effect under an armed chaos plan
        # — they are not effects the calling path performs.  The
        # ``fault-point`` base effect still marks every inject site,
        # and fault-scope bounds where those sites may live.
        if key[0].replace(os.sep, "/").endswith(
            "resilience/faults.py"
        ) and key[1] == "inject":
            return set()
        info = self.info_of[key]
        out: Set[Tuple[str, str]] = set()
        simple, qual = self._edges(self.scan_of[key[0]], info)
        for callee in simple:
            out.update(self._resolve_simple(key, callee))
        for mod, name in qual:
            out.update(self._resolve_qual(mod, name))
        # Nested defs run on behalf of their parent (thread targets,
        # callbacks) even when the reference never parses as a call.
        relpath, qualname = key
        out.update(
            k for k in self.info_of
            if k[0] == relpath and k[1].startswith(qualname + ".")
        )
        out.discard(key)
        return out

    def transitive_effects(self, key: Tuple[str, str]) -> Set[str]:
        """The inferred effect signature: every effect reachable from
        ``key`` through the call graph (the bottom-up closure)."""
        seen = {key}
        frontier = [key]
        effects: Set[str] = set(self.base.get(key, ()))
        while frontier:
            for nxt in self.succ.get(frontier.pop(), ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
                    effects |= set(self.base.get(nxt, ()))
        return effects


def _module_str_constants(scan: _ModuleScan) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for stmt in scan.tree.body:
        if isinstance(stmt, ast.Assign) \
                and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt.value.value
    return out


def _base_effects(scan: _ModuleScan, info,
                  durable_choke: bool) -> Dict[str, Tuple[int, str]]:
    """One (line, detail) evidence per base effect of this function's
    own body (nested defs carry their own entries)."""
    out: Dict[str, Tuple[int, str]] = {}

    def note(effect: str, node: ast.AST, detail: str) -> None:
        if effect == "raw-fs-write" and durable_choke:
            effect = "durable-write"   # the choke point IS durable
        out.setdefault(
            effect, (getattr(node, "lineno", info.node.lineno), detail)
        )

    if info.jit_call is not None:
        note("jax-compile", info.node, "jit-decorated (trace entry)")
        note("jax-dispatch", info.node, "jit-decorated")

    nested: Set[ast.AST] = set()
    for sub in ast.walk(info.node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and sub is not info.node:
            nested.update(ast.walk(sub))
            nested.add(sub)

    for sub in ast.walk(info.node):
        if sub in nested:
            continue
        if isinstance(sub, ast.With):
            for item in sub.items:
                if _lockish_with_item(scan, item.context_expr):
                    note("lock-acquire", sub, "with <lock>")
        if isinstance(sub, ast.Subscript) and _is_os_environ(scan,
                                                             sub.value):
            note("env-read", sub, "os.environ[...]")
        if isinstance(sub, ast.Compare) and any(
            _is_os_environ(scan, c) for c in sub.comparators
        ):
            note("env-read", sub, "... in os.environ")
        if not isinstance(sub, ast.Call):
            continue
        if _jit_call_of(sub) is not None:
            note("jax-compile", sub, "jax.jit(...) call")
        f = sub.func
        if isinstance(f, ast.Name):
            b = _binding(scan, f.id)
            if f.id == "open" and _open_mode_writes(sub):
                note("raw-fs-write", sub, "open(.., write mode)")
            elif b:
                bmod, _, borig = b.rpartition(".")
                if b.startswith("jax"):
                    if borig in _JAX_COMPILE_ATTRS:
                        note("jax-compile", sub, f"{f.id}()")
                    note("jax-dispatch", sub, f"{f.id}()")
                elif bmod.startswith(_DURABLE_MODULE_PREFIXES) \
                        and borig in _DURABLE_FNS:
                    note("durable-write", sub, f"{borig}()")
                elif bmod == "subprocess" or b.startswith("subprocess."):
                    if borig in _SUBPROCESS_FNS:
                        note("spawn", sub, f"subprocess.{borig}")
                elif b.endswith("faults.inject") or (
                    bmod.endswith("resilience.faults")
                    and borig == "inject"
                ):
                    note("fault-point", sub, "faults.inject()")
        elif isinstance(f, ast.Attribute):
            a = f.attr
            recv = f.value
            rb = (_binding(scan, recv.id)
                  if isinstance(recv, ast.Name) else None)
            if rb is not None:
                if rb == "jax" or rb.startswith("jax."):
                    if a in _JAX_COMPILE_ATTRS:
                        note("jax-compile", sub, f"{recv.id}.{a}()")
                        note("jax-dispatch", sub, f"{recv.id}.{a}()")
                    elif a not in ("config",):
                        note("jax-dispatch", sub, f"{recv.id}.{a}()")
                elif rb == "time" and a == "sleep":
                    note("blocking-io", sub, "time.sleep()")
                elif rb == "select" and a == "select":
                    note("blocking-io", sub, "select.select()")
                elif rb == "subprocess" and a in _SUBPROCESS_FNS:
                    note("spawn", sub, f"subprocess.{a}")
                elif rb == "os" and a in _RAW_OS_FNS:
                    note("raw-fs-write", sub, f"os.{a}()")
                elif rb == "os" and a in _OS_SPAWN_FNS:
                    note("spawn", sub, f"os.{a}()")
                elif rb == "os" and a == "getenv":
                    note("env-read", sub, "os.getenv()")
                elif rb == "shutil" and a in _SHUTIL_WRITE_FNS:
                    note("raw-fs-write", sub, f"shutil.{a}()")
                elif (rb in ("numpy", "json", "pickle")
                      or rb.startswith("numpy.")) and (
                    a in _NP_SAVE_FNS or a == "dump"
                ):
                    note("raw-fs-write", sub, f"{recv.id}.{a}()")
                elif (rb.startswith(_DURABLE_MODULE_PREFIXES)
                      and a in _DURABLE_FNS):
                    note("durable-write", sub, f"{recv.id}.{a}()")
                elif (rb.endswith("resilience.faults")
                      or rb.endswith(".faults")) and a == "inject":
                    note("fault-point", sub, "faults.inject()")
            if _is_os_environ(scan, recv) \
                    and a in ("get", "pop", "setdefault"):
                note("env-read", sub, f"os.environ.{a}()")
            if a == "acquire":
                note("lock-acquire", sub, ".acquire()")
            elif a == "block_until_ready":
                note("jax-dispatch", sub, ".block_until_ready()")
            elif a in _BLOCKING_METHODS:
                note("blocking-io", sub, f".{a}()")
    return out


def scan_package(root: str,
                 package_dir: Optional[str] = None) -> _EffectGraph:
    package_dir = package_dir or os.path.join(root, "tsspark_tpu")
    scans: List[_ModuleScan] = []
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r") as fh:
                    source = fh.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError):
                continue   # tracelint owns parse-error findings
            scan = _ModuleScan(os.path.relpath(path, root), tree, source)
            _walk_functions(scan)
            scans.append(scan)
    return _EffectGraph(scans)


# ---------------------------------------------------------------------------
# path budgets
# ---------------------------------------------------------------------------

def _check_budgets(graph: _EffectGraph, config: EffectsConfig,
                   findings: List[Finding]) -> None:
    for budget in config.paths:
        cuts: Set[Tuple[str, str]] = set()
        for ref in budget.allow_via:
            key = _parse_ref(ref, f"budget {budget.name!r}")
            if key not in graph.info_of:
                findings.append(Finding(
                    "effect-model", "pyproject.toml", 0, budget.name,
                    f"allow_via {ref!r} matches no package function — "
                    "a stale cut point must die with the code it "
                    "excused",
                ))
            cuts.add(key)
        forbid = set(budget.forbid)
        for ref in budget.roots:
            root_key = _parse_ref(ref, f"budget {budget.name!r}")
            if root_key not in graph.info_of:
                findings.append(Finding(
                    "effect-model", "pyproject.toml", 0, budget.name,
                    f"root {ref!r} matches no package function — a "
                    "budget checking nothing passes vacuously",
                ))
                continue
            # BFS from the root, skipping declared cut points, with
            # parent pointers for the reported chain.
            parent: Dict[Tuple[str, str], Tuple[str, str]] = {}
            seen = {root_key}
            frontier = [root_key]
            while frontier:
                cur = frontier.pop(0)
                hit = forbid & set(graph.base.get(cur, ()))
                for eff in sorted(hit):
                    line, detail = graph.base[cur][eff]
                    chain: List[str] = []
                    k = cur
                    while k in parent:
                        chain.append(k[1])
                        k = parent[k]
                    chain.append(root_key[1])
                    scan = graph.scan_of[cur[0]]
                    if not scan.line_ok(line, "effect-budget"):
                        findings.append(Finding(
                            "effect-budget", cur[0], line, cur[1],
                            f"path {budget.name!r} must not reach "
                            f"{eff!r} but does ({detail}) via "
                            + " <- ".join(chain),
                        ))
                for nxt in sorted(graph.succ.get(cur, ())):
                    if nxt not in seen and nxt not in cuts:
                        seen.add(nxt)
                        parent[nxt] = cur
                        frontier.append(nxt)


# ---------------------------------------------------------------------------
# env-var contract
# ---------------------------------------------------------------------------

def _resolve_env_arg(graph: _EffectGraph, scan: _ModuleScan,
                     node: ast.AST) -> Optional[str]:
    """The env-var NAME an expression denotes: a literal, a module
    constant, or an imported module's constant (``faults.ENV_VAR``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return graph.constants.get(scan.relpath, {}).get(node.id)
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name):
        b = _binding(scan, node.value.id)
        if b is not None:
            other = graph.by_dotted.get(b)
            if other is not None:
                return graph.constants.get(other.relpath,
                                           {}).get(node.attr)
    return None


def _env_read_sites(graph: _EffectGraph, scan: _ModuleScan
                    ) -> List[Tuple[int, str, str]]:
    """(line, var, qualname) for every resolvable env READ in the
    module — module-level code included (qualname ``<module>``)."""
    sites: List[Tuple[int, str, str]] = []

    def visit(node: ast.AST, qual: str) -> None:
        for child in ast.iter_child_nodes(node):
            cq = qual
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                cq = f"{qual}.{child.name}" if qual != "<module>" \
                    else child.name
            elif isinstance(child, ast.ClassDef):
                cq = f"{qual}.{child.name}" if qual != "<module>" \
                    else child.name
            arg = None
            if isinstance(child, ast.Subscript) \
                    and isinstance(child.ctx, ast.Load) \
                    and _is_os_environ(scan, child.value):
                arg = child.slice
            elif isinstance(child, ast.Call):
                f = child.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in ("get", "pop", "setdefault") \
                        and _is_os_environ(scan, f.value):
                    arg = child.args[0] if child.args else None
                elif isinstance(f, ast.Attribute) and f.attr == "getenv" \
                        and isinstance(f.value, ast.Name) \
                        and _binding(scan, f.value.id) == "os":
                    arg = child.args[0] if child.args else None
            elif isinstance(child, ast.Compare) and any(
                _is_os_environ(scan, c) for c in child.comparators
            ):
                arg = child.left
            if arg is not None:
                var = _resolve_env_arg(graph, scan, arg)
                if var is not None:
                    sites.append((child.lineno, var, qual))
            visit(child, cq)

    visit(scan.tree, "<module>")
    return sites


def _inherit_all_builders(graph: _EffectGraph) -> Set[str]:
    """Simple names of functions that RETURN an environment seeded from
    the parent's (``env = dict(os.environ) ... return env``) — the
    ``_child_env`` idiom every spawn site routes through."""
    builders: Set[str] = set()
    for key, info in graph.info_of.items():
        seeded: Set[str] = set()
        returned = False
        for sub in ast.walk(info.node):
            if isinstance(sub, ast.Assign) \
                    and _seeds_from_environ(graph.scan_of[key[0]],
                                            sub.value):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        seeded.add(t.id)
            if isinstance(sub, ast.Return) and sub.value is not None:
                if isinstance(sub.value, ast.Name) \
                        and sub.value.id in seeded:
                    returned = True
                elif _seeds_from_environ(graph.scan_of[key[0]],
                                         sub.value):
                    returned = True
        if returned:
            builders.add(key[1].rsplit(".", 1)[-1])
    return builders


def _seeds_from_environ(scan: _ModuleScan, value: ast.AST) -> bool:
    """``dict(os.environ)`` / ``os.environ.copy()`` / ``{**os.environ}``."""
    if isinstance(value, ast.Call):
        f = value.func
        if isinstance(f, ast.Name) and f.id == "dict" and value.args \
                and _is_os_environ(scan, value.args[0]):
            return True
        if isinstance(f, ast.Attribute) and f.attr == "copy" \
                and _is_os_environ(scan, f.value):
            return True
    if isinstance(value, ast.Dict):
        return any(k is None and _is_os_environ(scan, v)
                   for k, v in zip(value.keys, value.values))
    return False


def _check_env_contract(graph: _EffectGraph, config: EffectsConfig,
                        scope_rel: Optional[Set[str]],
                        findings: List[Finding], root: str) -> None:
    registered = {spec.var: spec for spec in config.env}
    inherited = sorted(v for v, s in registered.items() if s.inherit)
    builders = _inherit_all_builders(graph)
    seen_vars: Set[str] = set()

    for scan in graph.scans:
        in_scope = scope_rel is None or scan.relpath in scope_rel
        for line, var, qual in _env_read_sites(graph, scan):
            if not var.startswith("TSSPARK_"):
                continue
            seen_vars.add(var)
            if var not in registered and in_scope \
                    and not scan.line_ok(line, "env-unregistered"):
                findings.append(Finding(
                    "env-unregistered", scan.relpath, line, qual,
                    f"reads {var!r}, which is not in the EnvSpec table "
                    "([tool.tsspark.analysis.effects.env]): register "
                    "its owner and child-propagation rule",
                ))
        if not in_scope:
            continue
        for key, info in graph.info_of.items():
            if key[0] != scan.relpath:
                continue
            for sub in ast.walk(info.node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                is_spawn = (
                    (isinstance(f, ast.Attribute)
                     and f.attr in _SUBPROCESS_FNS
                     and isinstance(f.value, ast.Name)
                     and _binding(scan, f.value.id) == "subprocess")
                    or (isinstance(f, ast.Name)
                        and (_binding(scan, f.id) or "")
                        .startswith("subprocess."))
                )
                if not is_spawn:
                    continue
                env_kw = next((kw.value for kw in sub.keywords
                               if kw.arg == "env"), None)
                if env_kw is None:
                    continue   # child inherits the whole parent env
                if _env_provably_inherits(graph, scan, info, env_kw,
                                          builders):
                    continue
                if not scan.line_ok(sub.lineno, "env-propagation"):
                    findings.append(Finding(
                        "env-propagation", scan.relpath, sub.lineno,
                        key[1],
                        "spawn passes env= not provably seeded from "
                        "os.environ; inherited EnvSpecs would be "
                        f"dropped ({', '.join(inherited) or 'none'}) — "
                        "seed with dict(os.environ) or a _child_env "
                        "builder",
                    ))

    if scope_rel is None:
        for var, spec in sorted(registered.items()):
            if var not in seen_vars:
                findings.append(Finding(
                    "env-unused", "pyproject.toml", 0, var,
                    "EnvSpec registers a variable nothing reads — a "
                    "stale spec must die with the read it covered "
                    f"(declared owner: {spec.owner})",
                ))
            elif not os.path.exists(os.path.join(root, spec.owner)):
                findings.append(Finding(
                    "effect-model", "pyproject.toml", 0, var,
                    f"EnvSpec owner {spec.owner!r} does not exist",
                ))


def _env_provably_inherits(graph: _EffectGraph, scan: _ModuleScan,
                           info, env_kw: ast.AST,
                           builders: Set[str]) -> bool:
    def is_builder_call(value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        f = value.func
        name = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        return name in builders

    if _seeds_from_environ(scan, env_kw) or is_builder_call(env_kw):
        return True
    if isinstance(env_kw, ast.Name):
        for sub in ast.walk(info.node):
            if isinstance(sub, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == env_kw.id
                for t in sub.targets
            ):
                if _seeds_from_environ(scan, sub.value) \
                        or is_builder_call(sub.value):
                    return True
    return False


# ---------------------------------------------------------------------------
# fault-point scoping
# ---------------------------------------------------------------------------

def _check_fault_scope(graph: _EffectGraph, config: EffectsConfig,
                       scope_rel: Optional[Set[str]],
                       findings: List[Finding]) -> None:
    declared = set(config.fault_modules)
    firing: Set[str] = set()
    for key, effects in graph.base.items():
        if "fault-point" not in effects:
            continue
        rel = key[0].replace(os.sep, "/")
        firing.add(rel)
        if rel in declared or rel.endswith("resilience/faults.py"):
            continue
        if scope_rel is not None and key[0] not in scope_rel:
            continue
        line, detail = graph.base[key]["fault-point"]
        if not graph.scan_of[key[0]].line_ok(line, "fault-scope"):
            findings.append(Finding(
                "fault-scope", key[0], line, key[1],
                f"{detail} in a module not declared in fault_modules "
                "([tool.tsspark.analysis.effects]): armable kill "
                "points must be a reviewed, enumerable surface",
            ))
    if scope_rel is None:
        for rel in sorted(declared - firing):
            findings.append(Finding(
                "effect-model", "pyproject.toml", 0, rel,
                "fault_modules declares a module with no "
                "faults.inject site — a stale declaration must die "
                "with the kill point it covered",
            ))


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def check_effects(
    root: str,
    config: Optional[EffectsConfig] = None,
    scope_paths: Optional[Sequence[str]] = None,
    package_dir: Optional[str] = None,
) -> List[Finding]:
    """The whole effects pass.  ``scope_paths`` (the ``--changed`` fast
    mode) narrows the per-site rules (env-unregistered,
    env-propagation, fault-scope) to the touched modules; the path
    budgets and the EnvSpec/fault tables are ALWAYS checked whole —
    a one-module edit can put a forbidden effect within reach of a
    root defined elsewhere, which is exactly what a path budget is
    for."""
    config = config if config is not None else load_config(root)
    graph = scan_package(root, package_dir)
    scope_rel: Optional[Set[str]] = None
    if scope_paths is not None:
        scope_rel = {os.path.relpath(p, root) for p in scope_paths}
    findings: List[Finding] = []
    _check_budgets(graph, config, findings)
    _check_env_contract(graph, config, scope_rel, findings, root)
    _check_fault_scope(graph, config, scope_rel, findings)
    return findings
