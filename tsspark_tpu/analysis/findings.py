"""The one finding currency every checker emits and the CLI prints."""

from __future__ import annotations

import dataclasses
from typing import Tuple

from tsspark_tpu.analysis.config import AnalysisSettings


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str       # e.g. "trace-branch", "non-atomic-write", "f64-leak"
    path: str       # repo-relative file path ("<kernel>" for contracts)
    line: int       # 1-based; 0 when the finding has no source anchor
    qualname: str   # enclosing function / kernel case name
    message: str

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.qualname}: {self.message}")


def apply_suppressions(
    findings: Tuple[Finding, ...], settings: AnalysisSettings
) -> Tuple[Tuple[Finding, ...], Tuple[Finding, ...]]:
    """(kept, suppressed) after the committed baseline.  Inline
    ``# lint-ok[rule]:`` suppressions are applied by the checkers
    themselves (they need source lines); this handles the pyproject
    baseline, which matches on (rule, relpath, qualname)."""
    keys = set(settings.suppression_keys())
    kept, suppressed = [], []
    for f in findings:
        if (f.rule, f.path, f.qualname) in keys:
            suppressed.append(f)
        else:
            kept.append(f)
    return tuple(kept), tuple(suppressed)
