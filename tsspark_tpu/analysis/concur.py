"""Concurrency lint: lock discipline, thread lifecycle, mmap aliasing.

The review-hardening lists of PRs 10/13/14 were dominated by bug
classes a machine can find mechanically: counters bumped without the
lock that guards them elsewhere (the ``wrong_version`` fix), locks held
across multi-second blocking calls (the respawn-under-``_lock`` fix),
thread targets that swallow failures (the publisher-join fix), and
in-place mutation of shared mmap planes (the PR 13 shared-plane
hazard).  This module turns each into a tier-1 gate rule:

* ``lock-guard`` — per-class inference of guarded attributes.  An
  attribute WRITTEN under ``with self.<lock>:`` in one method is
  "guarded by that lock"; in a class that runs code on more than one
  thread (it spawns via ``Thread(target=...)``/``Timer`` — external
  caller threads cannot be seen statically), any other write to that
  attribute outside the lock is a finding, ``__init__`` excepted (the
  constructor runs before any thread exists).  Methods whose every
  intra-class call site already holds the lock are treated as entered
  with it held (the ``refresh``-under-``pump`` pattern).  Reads are
  deliberately not linted — stats/snapshot reads of monotonic counters
  are benign and would bury the signal.
* ``lock-blocking`` — a blocking call directly inside a ``with
  <lock>:`` body: ``time.sleep`` at/over 100 ms (or a non-constant
  delay), ``subprocess.run``/``check_call``/``check_output``,
  ``select.select``, socket ``recv``/``sendall``/``accept``/
  ``connect``, ``.wait(...)``/``.join(...)`` on things that are not a
  Condition (Condition.wait releases the lock; ``str.join`` is
  excluded by argument shape).  Only DIRECT calls in the ``with`` body
  are flagged — serializing one slow I/O op behind a dedicated lock is
  a legitimate idiom, so the rule targets locks that also guard state.
* ``thread-join`` — every ``threading.Thread``/``Timer`` spawned must
  be joined somewhere in its module (matched through the names/attrs
  the thread object flows to), or be ``daemon=True`` WITH an inline
  waiver explaining why abandonment is safe.
* ``thread-exc`` — a thread target (resolved intra-module) must
  contain a broad exception handler (``except Exception``/
  ``BaseException``/bare) that stashes, counts, or reports the
  failure.  A target whose only handlers are narrow lets an unexpected
  failure kill the thread silently — the publisher-thread bug class
  PR 14 fixed by hand.
* ``mmap-alias`` — arrays originating from READ-ONLY attaches
  (``np.load(..., mmap_mode="r")``, ``open_memmap(..., mode="r")``,
  ``snapplane.attach``, ``plane.open_batch``) must never flow into an
  in-place mutation site (``x[...] = ``, ``x += ``, ``np.copyto``
  dst, ``.sort()``/``.fill()``/``.partition()``) within the function.
  Taint propagates through assignment, attribute/subscript access and
  ``np.asarray`` (the one numpy entry point that does NOT copy); any
  other call (``np.array``, ``.copy()``, ``.astype()``, ...) is
  assumed to return fresh memory and launders the view — conservative
  against false positives, and the sanctioned copy-first fix is
  exactly such a call.

All rules honor the inline ``# lint-ok[rule]: reason`` waiver on the
flagged line (for ``lock-blocking``, also on the enclosing ``with``
line, so one justified lifecycle lock does not need a waiver per
statement) and the pyproject baseline.  Like every static pass here,
the margins are heuristic BY DESIGN: the contract is zero unexplained
findings, not zero waivers.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tsspark_tpu.analysis.findings import Finding
from tsspark_tpu.analysis.tracelint import _ModuleScan, _walk_functions

#: time.sleep at or over this many seconds inside a lock is a finding.
SLEEP_THRESHOLD_S = 0.1

_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTORS = {"Condition"}
_THREAD_CTORS = {"Thread", "Timer"}
_BLOCKING_SUBPROCESS = {"run", "check_call", "check_output", "call"}
_SOCKET_BLOCKING = {"recv", "sendall", "accept", "connect"}
# In-place ndarray mutators (beyond subscript/augmented assignment).
_INPLACE_METHODS = {"sort", "fill", "partition", "put"}
_TAINT_SOURCES = {"attach", "open_batch"}


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _is_threading_ctor(node: ast.Call, ctors: Set[str]) -> bool:
    """``threading.Thread(...)`` / bare ``Thread(...)`` etc."""
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id == "threading" and f.attr in ctors
    return isinstance(f, ast.Name) and f.id in ctors


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X`` (None otherwise)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _target_names(target: ast.AST) -> List[str]:
    """Plain names (and self-attrs, prefixed ``self.``) a value is
    assigned to."""
    out: List[str] = []
    if isinstance(target, ast.Name):
        out.append(target.id)
    elif isinstance(target, ast.Tuple):
        for e in target.elts:
            out += _target_names(e)
    else:
        sa = _self_attr(target)
        if sa is not None:
            out.append(f"self.{sa}")
    return out


# ---------------------------------------------------------------------------
# per-class lock model
# ---------------------------------------------------------------------------


class _ClassModel:
    """Lock/thread facts for one class definition."""

    def __init__(self, name: str):
        self.name = name
        self.lock_attrs: Set[str] = set()      # threading.Lock/RLock
        self.cond_attrs: Set[str] = set()      # threading.Condition
        self.methods: Dict[str, ast.FunctionDef] = {}
        #: method -> simple names of intra-class methods it calls
        self.calls: Dict[str, Set[str]] = {}
        #: method -> locks held at EVERY intra-class call site (None =
        #: never called intra-class)
        self.entry_locks: Dict[str, Optional[Set[str]]] = {}
        #: methods used as Thread(target=self.m) entry points
        self.thread_entries: Set[str] = set()
        #: methods containing a Thread(...) spawn (their nested targets
        #: run on the new thread)
        self.spawner_methods: Set[str] = set()


def _collect_classes(tree: ast.Module) -> List[Tuple[str, ast.ClassDef]]:
    out = []

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                out.append((f"{prefix}{child.name}", child))
                visit(child, f"{prefix}{child.name}.")
            elif not isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                visit(child, prefix)

    visit(tree, "")
    return out


def _build_class_model(qual: str, cls: ast.ClassDef) -> _ClassModel:
    model = _ClassModel(qual)
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            model.methods[stmt.name] = stmt
    # Lock attribute discovery: self.X = threading.Lock()/RLock()/
    # Condition() anywhere in any method (usually __init__).
    for m in model.methods.values():
        for sub in ast.walk(m):
            if isinstance(sub, ast.Assign) and isinstance(sub.value,
                                                          ast.Call):
                for t in sub.targets:
                    sa = _self_attr(t)
                    if sa is None:
                        continue
                    if _is_threading_ctor(sub.value, _LOCK_CTORS):
                        model.lock_attrs.add(sa)
                    elif _is_threading_ctor(sub.value, _COND_CTORS):
                        model.cond_attrs.add(sa)
    # Intra-class call graph + thread entry points.
    for name, m in model.methods.items():
        calls: Set[str] = set()
        for sub in ast.walk(m):
            if not isinstance(sub, ast.Call):
                continue
            sa = _self_attr(sub.func)
            if sa is not None and sa in model.methods:
                calls.add(sa)
            if _is_threading_ctor(sub, _THREAD_CTORS):
                model.spawner_methods.add(name)
                for kw in sub.keywords:
                    if kw.arg == "target":
                        tsa = _self_attr(kw.value)
                        if tsa is not None and tsa in model.methods:
                            model.thread_entries.add(tsa)
                        elif isinstance(kw.value, ast.Name):
                            # Thread(target=local_fn): the nested def's
                            # own self-method calls run on the thread.
                            for nd in ast.walk(m):
                                if isinstance(nd, ast.FunctionDef) \
                                        and nd.name == kw.value.id:
                                    for c in ast.walk(nd):
                                        if isinstance(c, ast.Call):
                                            csa = _self_attr(c.func)
                                            if csa in model.methods:
                                                model.thread_entries \
                                                    .add(csa)
                # Timer(delay, fn): positional callback.
                if (_is_threading_ctor(sub, {"Timer"})
                        and len(sub.args) > 1):
                    tsa = _self_attr(sub.args[1])
                    if tsa is not None and tsa in model.methods:
                        model.thread_entries.add(tsa)
        model.calls[name] = calls
    return model


def _held_locks_at_calls(model: _ClassModel) -> None:
    """Fill ``entry_locks``: for each method, the set of lock attrs held
    at EVERY intra-class call site (so a method only ever invoked under
    a lock is analyzed as entered with it held)."""
    sites: Dict[str, List[Set[str]]] = {m: [] for m in model.methods}

    def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            add: List[str] = []
            for item in node.items:
                sa = _self_attr(item.context_expr)
                if sa is not None and sa in (model.lock_attrs
                                             | model.cond_attrs):
                    add.append(sa)
            inner = held + tuple(add)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, ast.Call):
            sa = _self_attr(node.func)
            if sa is not None and sa in model.methods:
                sites[sa].append(set(held))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs execute later (often on another thread):
            # locks held at definition are NOT held at run time.
            held = ()
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for m in model.methods.values():
        for stmt in m.body:
            visit(stmt, ())
    for name, call_sites in sites.items():
        if not call_sites:
            model.entry_locks[name] = None
        else:
            common = set(call_sites[0])
            for s in call_sites[1:]:
                common &= s
            model.entry_locks[name] = common


def _multi_thread_class(model: _ClassModel) -> bool:
    """Does this class run code on more than one thread?  True when it
    spawns any thread — once it does, every non-constructor method is
    potentially concurrent with the spawned ones (and external caller
    threads cannot be seen statically anyway).  A class that never
    spawns has no intra-class concurrency: defensive API locking in a
    single-threaded class is not linted."""
    return bool(model.thread_entries or model.spawner_methods)


def _guarded_writes(model: _ClassModel) -> Dict[str, Set[str]]:
    """attr -> lock names it is written under somewhere in the class.
    Conditions count as locks here: ``with self._cond:`` holds the
    condition's underlying mutex, so writes under it are guarded by it
    exactly like a plain Lock."""
    guarded: Dict[str, Set[str]] = {}
    mutexes = model.lock_attrs | model.cond_attrs

    def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            add = [sa for item in node.items
                   for sa in [_self_attr(item.context_expr)]
                   if sa is not None and sa in mutexes]
            inner = held + tuple(add)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if held and isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                sa = _self_attr(t)
                if sa is not None and sa not in mutexes:
                    guarded.setdefault(sa, set()).update(held)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            held = ()
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for name, m in model.methods.items():
        entry = model.entry_locks.get(name) or set()
        for stmt in m.body:
            visit(stmt, tuple(sorted(entry)))
    return guarded


def _check_lock_guard(scan: _ModuleScan, qual: str, model: _ClassModel,
                      findings: List[Finding]) -> None:
    _held_locks_at_calls(model)
    guarded = _guarded_writes(model)
    if not guarded or not _multi_thread_class(model):
        return

    mutexes = model.lock_attrs | model.cond_attrs

    def visit(name: str, node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            add = [sa for item in node.items
                   for sa in [_self_attr(item.context_expr)]
                   if sa is not None and sa in mutexes]
            inner = held + tuple(add)
            for stmt in node.body:
                visit(name, stmt, inner)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                sa = _self_attr(t)
                if (sa is not None and sa in guarded
                        and not (set(held) & guarded[sa])
                        and not scan.line_ok(node.lineno, "lock-guard")):
                    locks = "/".join(sorted(guarded[sa]))
                    findings.append(Finding(
                        "lock-guard", scan.relpath, node.lineno,
                        f"{qual}.{name}",
                        f"write to self.{sa} without {locks} (held at "
                        "other writes of this attribute; this class "
                        "runs on multiple threads, so the unguarded "
                        "write can interleave with — or hide — a "
                        "guarded one)",
                    ))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            held = ()
        for child in ast.iter_child_nodes(node):
            visit(name, child, held)

    for name, m in sorted(model.methods.items()):
        if name == "__init__":
            continue  # constructor runs before any thread exists
        entry = model.entry_locks.get(name) or set()
        for stmt in m.body:
            visit(name, stmt, tuple(sorted(entry)))


# ---------------------------------------------------------------------------
# blocking calls under a lock
# ---------------------------------------------------------------------------


def _blocking_reason(node: ast.Call,
                     cond_attrs: Set[str]) -> Optional[str]:
    """Why this call blocks (None when it does not / cannot be told)."""
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        mod, attr = f.value.id, f.attr
        if mod == "time" and attr == "sleep":
            if node.args and isinstance(node.args[0], ast.Constant):
                try:
                    if float(node.args[0].value) < SLEEP_THRESHOLD_S:
                        return None
                except (TypeError, ValueError):
                    pass
            return "time.sleep"
        if mod == "subprocess" and attr in _BLOCKING_SUBPROCESS:
            return f"subprocess.{attr}"
        if mod == "select" and attr == "select":
            return "select.select"
    if isinstance(f, ast.Attribute):
        if f.attr in _SOCKET_BLOCKING:
            return f".{f.attr}()"
        if f.attr == "join":
            # str.join: a string-literal receiver, an argument that is
            # clearly an iterable CONSTRUCTION, or more than one
            # positional argument (Thread/Process.join takes at most
            # one — os.path.join(a, b) must never flag).  An `os.path`
            # receiver is exempt outright.  Everything else — bare
            # t.join(), t.join(5.0), t.join(self.grace_s),
            # join(timeout=...) — is treated as a thread/process join
            # (the multi-second-block-under-lock class); a genuine
            # sep.join(parts) under a lock takes a waiver.
            if isinstance(f.value, ast.Constant) \
                    and isinstance(f.value.value, str):
                return None
            if isinstance(f.value, ast.Attribute) \
                    and f.value.attr == "path":
                return None
            if len(node.args) >= 2:
                return None
            if (len(node.args) == 1 and not node.keywords
                    and isinstance(node.args[0],
                                   (ast.ListComp, ast.GeneratorExp,
                                    ast.List, ast.Tuple, ast.Set,
                                    ast.Call, ast.Starred))):
                return None
            return ".join(...)"
        if f.attr == "wait":
            # Condition.wait RELEASES the lock — never a finding.
            sa = _self_attr(f.value)
            if sa is not None and sa in cond_attrs:
                return None
            if sa is not None:
                # A known NON-Condition self attribute: bare .wait()
                # is an UNBOUNDED block under the lock — worse than a
                # timed one, flag it too.
                return ".wait(...)"
            # Plain x.wait() on a LOCAL name can't be told from a
            # Condition statically; only flag when a delay/timeout is
            # requested (Event.wait(t), proc.wait(timeout=...)).
            if node.args or any(kw.arg == "timeout"
                                for kw in node.keywords):
                return ".wait(...)"
    return None


def _check_lock_blocking(scan: _ModuleScan,
                         findings: List[Finding]) -> None:
    tree = scan.tree
    # self.<attr> Condition registry per class (to exempt cond.wait).
    cond_attrs: Set[str] = set()
    module_locks: Set[str] = set()
    for sub in ast.walk(tree):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            if _is_threading_ctor(sub.value, _COND_CTORS):
                for t in sub.targets:
                    sa = _self_attr(t)
                    if sa is not None:
                        cond_attrs.add(sa)
                    elif isinstance(t, ast.Name):
                        cond_attrs.add(t.id)
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value,
                                                       ast.Call):
            if _is_threading_ctor(stmt.value, _LOCK_CTORS):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        module_locks.add(t.id)

    def lockish(expr: ast.AST, local_locks: Set[str]) -> Optional[str]:
        # A mutex held by NAME: self._lock / pool._lock / a local or
        # module-level threading.Lock().  `self._locked()` (a Call) is
        # deliberately excluded — the flock-based file locks serialize
        # PROCESSES, where blocking the peer is the whole point.
        if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
            return expr.attr
        if isinstance(expr, ast.Name) and (
            expr.id in local_locks or expr.id in module_locks
        ):
            return expr.id
        if (isinstance(expr, ast.Call)
                and _is_threading_ctor(expr, _LOCK_CTORS)):
            return "anonymous lock"
        return None

    def visit_fn(fn: ast.AST, qual: str) -> None:
        local_locks: Set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and isinstance(sub.value,
                                                          ast.Call):
                if _is_threading_ctor(sub.value, _LOCK_CTORS):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            local_locks.add(t.id)

        def walk_with(node: ast.AST, lock_name: Optional[str],
                      with_line: int) -> None:
            if isinstance(node, ast.With):
                found = None
                for item in node.items:
                    found = found or lockish(item.context_expr,
                                             local_locks)
                if found is not None:
                    for stmt in node.body:
                        walk_with(stmt, found, node.lineno)
                    return
            if (lock_name is not None and isinstance(node, ast.Call)):
                why = _blocking_reason(node, cond_attrs)
                if why is not None \
                        and not scan.line_ok(node.lineno,
                                             "lock-blocking") \
                        and not scan.line_ok(with_line, "lock-blocking"):
                    findings.append(Finding(
                        "lock-blocking", scan.relpath, node.lineno,
                        qual,
                        f"{why} while holding {lock_name}: every other "
                        "thread contending this lock stalls for the "
                        "full blocking window (move the call outside "
                        "the critical section, or waive with the "
                        "reason the stall is acceptable)",
                    ))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                return  # nested defs run later, lock not held
            for child in ast.iter_child_nodes(node):
                walk_with(child, lock_name, with_line)

        for stmt in fn.body:
            walk_with(stmt, None, fn.lineno)

    for qual, info in scan.functions.items():
        visit_fn(info.node, qual)


# ---------------------------------------------------------------------------
# thread lifecycle
# ---------------------------------------------------------------------------


def _broad_handler(fn: ast.AST) -> bool:
    """Does the function ITSELF contain a broad except (Exception /
    BaseException / bare) — the minimum bar for 'failures cannot escape
    this thread target silently'?  Nested defs are excluded: a handler
    inside a helper the target spawns does not protect the target."""
    nested = {
        id(s) for n in ast.walk(fn)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n is not fn
        for s in ast.walk(n)
    }
    for sub in ast.walk(fn):
        if id(sub) in nested:
            continue
        if isinstance(sub, ast.ExceptHandler):
            t = sub.type
            if t is None:
                return True
            names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
            for n in names:
                base = n.attr if isinstance(n, ast.Attribute) else (
                    n.id if isinstance(n, ast.Name) else None
                )
                if base in ("Exception", "BaseException"):
                    return True
    return False


def _check_threads(scan: _ModuleScan, findings: List[Finding]) -> None:
    tree = scan.tree
    qualnames: Dict[int, str] = {
        id(info.node): qual for qual, info in scan.functions.items()
    }
    # All join targets in the module: X.join(...) / self.X.join(...).
    join_names: Set[str] = set()
    for sub in ast.walk(tree):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "join"):
            recv = sub.func.value
            if isinstance(recv, ast.Name):
                join_names.add(recv.id)
            else:
                sa = _self_attr(recv)
                if sa is not None:
                    join_names.add(f"self.{sa}")

    for qual, info in scan.functions.items():
        fn = info.node
        nested = {
            id(s) for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
            for s in ast.walk(n)
        }
        for stmt in ast.walk(fn):
            if id(stmt) in nested:
                continue
            spawn: Optional[ast.Call] = None
            aliases: List[str] = []
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call) \
                    and _is_threading_ctor(stmt.value, _THREAD_CTORS):
                spawn = stmt.value
                for t in stmt.targets:
                    aliases += _target_names(t)
            elif isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Call):
                # Thread(...).start() fire-and-forget (no alias at all).
                call = stmt.value
                if (isinstance(call.func, ast.Attribute)
                        and call.func.attr == "start"
                        and isinstance(call.func.value, ast.Call)
                        and _is_threading_ctor(call.func.value,
                                               _THREAD_CTORS)):
                    spawn = call.func.value
                elif _is_threading_ctor(call, _THREAD_CTORS):
                    spawn = call
            if spawn is None:
                continue
            # Follow one level of aliasing: t = Thread(...); self.x = t.
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id in aliases:
                    for t in sub.targets:
                        aliases += _target_names(t)
            daemon = any(
                kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True for kw in spawn.keywords
            )
            joined = any(a in join_names for a in aliases)
            if not joined and not scan.line_ok(spawn.lineno,
                                               "thread-join"):
                what = ("daemon thread" if daemon
                        else "non-daemon thread")
                findings.append(Finding(
                    "thread-join", scan.relpath, spawn.lineno, qual,
                    f"{what} spawned here is never joined in this "
                    "module: its failure (and its in-flight work) is "
                    "invisible to every exit path of the owner — join "
                    "it, or waive with the reason abandonment is safe",
                ))
            # Resolve the target for the exception-escape rule.
            target_fn: Optional[ast.AST] = None
            target_name = None
            for kw in spawn.keywords:
                if kw.arg == "target":
                    sa = _self_attr(kw.value)
                    if sa is not None:
                        target_name = sa
                    elif isinstance(kw.value, ast.Name):
                        target_name = kw.value.id
            if target_name is not None:
                for tqual, tinfo in scan.functions.items():
                    if tqual == target_name or tqual.endswith(
                        "." + target_name
                    ):
                        target_fn = tinfo.node
                        target_qual = tqual
                        break
            if target_fn is not None and not _broad_handler(target_fn) \
                    and not scan.line_ok(target_fn.lineno,
                                         "thread-exc") \
                    and not scan.line_ok(spawn.lineno, "thread-exc"):
                findings.append(Finding(
                    "thread-exc", scan.relpath, target_fn.lineno,
                    target_qual,
                    "thread target has no broad exception handler: an "
                    "unexpected failure kills the thread with only a "
                    "stderr traceback — stash the error for the "
                    "joiner, count it, or flip the owner's stop/fenced "
                    "state so the failure is observable",
                ))


# ---------------------------------------------------------------------------
# mmap aliasing
# ---------------------------------------------------------------------------


def _is_readonly_attach(node: ast.Call) -> bool:
    name = _call_name(node)
    if name in _TAINT_SOURCES:
        return True
    if name in ("load", "open_memmap"):
        for kw in node.keywords:
            if kw.arg in ("mmap_mode", "mode") \
                    and isinstance(kw.value, ast.Constant) \
                    and kw.value.value == "r":
                return True
    return False


def _expr_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    """Is this expression a view of a read-only attach?"""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        sa = _self_attr(node)
        if sa is not None:
            return f"self.{sa}" in tainted
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Subscript):
        # A basic slice of an mmap is a view; fancy indexing copies,
        # but conservatively treat both as views (cleansing calls are
        # the sanctioned way out).
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Call):
        if _is_readonly_attach(node):
            return True
        name = _call_name(node)
        if name == "asarray":
            # np.asarray does NOT copy: taint flows through.
            return bool(node.args) and _expr_tainted(node.args[0],
                                                     tainted)
        # Every other call is assumed to return fresh memory
        # (np.array/.copy()/.astype()/...): launders the view.
        return False
    return False


def _check_mmap_alias(scan: _ModuleScan,
                      findings: List[Finding]) -> None:
    for qual, info in scan.functions.items():
        fn = info.node
        tainted: Set[str] = set()

        def emit(node: ast.AST, what: str) -> None:
            if not scan.line_ok(node.lineno, "mmap-alias"):
                findings.append(Finding(
                    "mmap-alias", scan.relpath, node.lineno, qual,
                    f"{what} on an array attached read-only "
                    "(np.load mmap_mode='r' / plane attach): in-place "
                    "mutation of a shared mapped plane either raises "
                    "at runtime or corrupts every concurrent reader — "
                    "copy first (np.array / .copy() / .astype())",
                ))

        def visit(node: ast.AST) -> None:
            # In-order traversal: taint state is sequential (an `out =
            # np.array(mm)` must launder BEFORE `out[rows] = v` runs).
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                return  # nested defs are checked as their own function
            if isinstance(node, ast.Assign):
                is_src = _expr_tainted(node.value, tainted)
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and _expr_tainted(t.value, tainted):
                        emit(node, "subscript assignment")
                    for name in _target_names(t):
                        if is_src:
                            tainted.add(name)
                        else:
                            tainted.discard(name)
            elif isinstance(node, ast.AugAssign):
                t = node.target
                base = t.value if isinstance(t, ast.Subscript) else t
                if _expr_tainted(base, tainted):
                    emit(node, "augmented assignment")
            elif isinstance(node, ast.Call):
                name = _call_name(node)
                if name == "copyto" and node.args \
                        and _expr_tainted(node.args[0], tainted):
                    emit(node, "np.copyto destination")
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _INPLACE_METHODS
                        and _expr_tainted(node.func.value, tainted)):
                    emit(node, f".{node.func.attr}()")
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def check_paths(paths: Sequence[str], root: str) -> List[Finding]:
    """All five concurrency rules over the given files."""
    findings: List[Finding] = []
    for path in paths:
        with open(path, "r") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue  # tracelint owns the parse-error finding
        scan = _ModuleScan(os.path.relpath(path, root), tree, source)
        _walk_functions(scan)
        for qual, cls in _collect_classes(tree):
            model = _build_class_model(qual, cls)
            if model.lock_attrs or model.cond_attrs:
                _check_lock_guard(scan, qual, model, findings)
        _check_lock_blocking(scan, findings)
        _check_threads(scan, findings)
        _check_mmap_alias(scan, findings)
    return findings


def check_package(root: str, package_dir: str) -> List[Finding]:
    paths = []
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))
    return check_paths(sorted(paths), root)
