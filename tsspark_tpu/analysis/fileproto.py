"""File-protocol race checker: the orchestrator/streaming/checkpoint
artifact lifecycle, verified statically.

The multi-process fit protocol (orchestrate.py) is a filesystem
conversation: workers claim series ranges and publish ``chunk_*.npz``
results, a prep child publishes ``prep_*.npz`` payload caches, the
parent reads coverage and sentinels, the integrity sweep quarantines
``*.corrupt`` files, checkpoints persist fitted state across processes.
Its two safety properties are checked here with zero processes spawned:

1. **Atomicity** — every writer of a protocol artifact goes through the
   shared write-temp-then-rename helper (``utils.atomic``) or the
   manual temp+``os.replace`` idiom, so a reader can never observe a
   torn file.  An AST pass over the protocol modules finds every write
   site (``open(..., "w")``, ``np.save*``/``json.dump``/``pickle.dump``
   on a path), attributes it to an artifact from the committed registry
   below, and flags:

   * ``non-atomic-write`` — a protocol artifact written without the
     atomic idiom;
   * ``unregistered-artifact`` — a write whose target matches no
     registry entry (new artifacts must be registered WITH their
     lifecycle story, or they silently escape both checks);
   * ``foreign-writer`` — a registered artifact written outside its
     declared owner functions (single-writer-per-artifact is what makes
     the lifecycle reasoning tractable).

2. **Range-claim disjointness** — a small-model check over the claim
   function itself (``orchestrate.plan_chunks``): for an enumerated
   space of completed-coverage states (bisected singles, resumed
   partial grids, chunk-size changes, 6-vs-7-digit filename regimes)
   the claims a worker would write are verified pairwise disjoint,
   inside the worker's window, and non-overlapping with existing
   coverage — the invariant that keeps two workers (or one worker and
   its own resumed past) from assembling duplicated series rows.
   ``completed_ranges``'s numeric ordering is model-checked with real
   files in a temp dir across the 999,999-series digit rollover.
"""

from __future__ import annotations

import ast
import dataclasses
import itertools
import os
import tempfile
from typing import Callable, List, Optional, Sequence, Set, Tuple

from tsspark_tpu.analysis.findings import Finding

# ---------------------------------------------------------------------------
# artifact registry: the committed lifecycle model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ArtifactSpec:
    """One protocol artifact class.

    ``markers``: string fragments that identify the artifact in a write
    site's path expression (static analysis sees the constants, not the
    runtime value).  ``writers``: qualnames (module-relative) allowed to
    write it.  ``append_ok``: append-mode log whose readers tolerate a
    torn last line (diagnostics, not protocol state).
    """

    name: str
    markers: Tuple[str, ...]
    writers: Tuple[str, ...]
    lifecycle: str
    append_ok: bool = False
    # Test-only machinery deliberately violating atomicity (the fault
    # injector corrupts files IN PLACE to prove readers survive it).
    exempt: bool = False


ARTIFACTS: Tuple[ArtifactSpec, ...] = (
    ArtifactSpec(
        "chunk-result", ("chunk_",),
        ("save_chunk_atomic",),
        "written once per claimed range by the fit worker (phase 1), "
        "patched in place by phase 2 / quarantine placeholders via the "
        "same helper; read by completed_ranges/load_fit_state; "
        "quarantined to *.corrupt on CRC mismatch",
    ),
    ArtifactSpec(
        "prep-cache", ("prep_",),
        ("save_prep_atomic",),
        "pure cache written by the CPU prep worker; consumed (and "
        "deleted) by the fit worker; corrupt copies dropped at load",
    ),
    ArtifactSpec(
        "run-config", ("runcfg.pkl",),
        ("save_run_config",),
        "written once by the parent before any child spawns; read-only "
        "to children",
    ),
    ArtifactSpec(
        "data-spill", (".npy",),
        ("spill_data", "create_columns", "write_shard", "import_batch"),
        "batch column files, two producers: orchestrate.spill_data "
        "writes them once (atomic) before any child spawns, and the "
        "data plane (data/plane.py) preallocates them as memmaps "
        "filled shard by shard — NOT atomic per write, but no reader "
        "ever touches column rows before the shard's sentinel "
        "(plane-shard-ok) has landed, so the sentinel is the unit of "
        "visibility; mmap'd read-only by children either way",
    ),
    ArtifactSpec(
        "heartbeat", ("heartbeat",),
        ("_fit_worker_body.heartbeat", "_resident_body.heartbeat"),
        "liveness mtime touched by the fit worker (and the mesh-resident "
        "program) per dispatch; read (mtime only) by the parent watchdog",
    ),
    ArtifactSpec(
        "phase2-sentinel", ("phase2_done",),
        ("_fit_worker_body", "_cpu_fill", "_resident_body"),
        "created exactly once when straggler coverage completes (or the "
        "run degrades to CPU); presence gates the parent's done check; "
        "removed only by the integrity re-queue path; the mesh-resident "
        "path writes the same marker so the two paths' scratch dirs are "
        "interchangeable",
    ),
    ArtifactSpec(
        "resident-state", ("resident.json",),
        ("_write_resident_state",),
        "mesh-resident flush progress (tsspark_tpu.resident): wave "
        "index, landed coverage, mesh shape — replaced atomically after "
        "every on-device -> checkpoint flush, so an operator (or the "
        "chaos harness proving the mesh path actually ran) never parses "
        "a torn record; the chunk files, not this artifact, carry the "
        "results",
    ),
    ArtifactSpec(
        "run-fingerprint", ("run_fingerprint",),
        ("fit_resilient",),
        "written once per fresh scratch dir; resume refuses a mismatch",
    ),
    ArtifactSpec(
        "quarantine", (".corrupt",),
        ("quarantine",),
        "os.replace of a failed chunk/prep file out of the resume "
        "globs (atomic by construction; kept for forensics)",
    ),
    ArtifactSpec(
        "chunk-lease", ("lease_",),
        ("claim_lease", "release_lease"),
        "fit-worker range lease (orchestrate.claim_lease): fresh claims "
        "are atomic O_EXCL creates, steals/renewals atomic replaces; a "
        "torn record (writer died mid-create) reads as stale and is "
        "stolen whole — readers tolerate it by design, and the save "
        "path fences on the lease token so a stolen range can never "
        "double-land",
        exempt=True,
    ),
    ArtifactSpec(
        "chaos-report", ("CHAOS_",),
        ("write_scorecard",),
        "chaos-storm scorecard (tsspark_tpu.chaos): injection schedule, "
        "invariant verdicts, MTTR per fault class; written once at "
        "storm end, atomic so a watcher never parses a partial JSON",
    ),
    ArtifactSpec(
        "span-log", ("spans.jsonl",),
        ("Run.write", "append_line"),
        "per-run observability span log (tsspark_tpu.obs): every "
        "process of a run appends whole lines through utils.atomic."
        "append_line (one O_APPEND write per record, so concurrent "
        "writers never interleave); readers tolerate a torn last line",
        append_ok=True,
    ),
    ArtifactSpec(
        "metrics-snapshot", ("metrics_",),
        ("MetricsRegistry.export",),
        "atomic metrics snapshot (obs.metrics): counters/gauges/pow-2 "
        "histograms exported once per process at run end, keyed into "
        "the run ledger by trace id; readers never see a torn JSON",
    ),
    ArtifactSpec(
        "run-ledger", ("RUNLEDGER_",),
        ("write_ledger",),
        "the joined observability ledger (obs.ledger): spans + metric "
        "snapshots + perf rows + report refs under one trace id, "
        "written once at run end, atomic so a watcher never parses a "
        "partial JSON",
    ),
    ArtifactSpec(
        "run-history", ("RUNHISTORY",),
        ("ingest", "append_line"),
        "the cross-run history index (obs.history): one normalized row "
        "per BENCH/SERVE/CHAOS/EVAL/RUNLEDGER artifact, appended "
        "crash-safely through utils.atomic.append_line (idempotent by "
        "trace id — concurrent entrypoints may self-ingest); readers "
        "tolerate a torn last line",
        append_ok=True,
    ),
    ArtifactSpec(
        "regression-verdict", ("REGRESSION_",),
        ("write_verdict",),
        "regression-sentinel verdict (obs.regress): the judged checks "
        "of one history row vs its rolling median/MAD baseline, "
        "written once per entrypoint run, atomic so a gate watching "
        "for the verdict never parses a partial JSON",
    ),
    ArtifactSpec(
        "chrome-trace", (),
        ("_chrome_trace",),
        "Chrome/Perfetto trace-event export of a run ledger's spans "
        "(python -m tsspark_tpu.obs report --chrome-trace): a pure "
        "derived view written once on demand, atomic; the span log "
        "stays the source of truth",
    ),
    # Specific marker specs must precede "checkpoint": its generic
    # ".json" marker would otherwise swallow "times.jsonl",
    # "manifest.json" and "SERVE_*.json" (first marker match wins).
    # The plane manifest must ALSO precede "registry-manifest": its
    # filename contains the "manifest.json" fragment.
    ArtifactSpec(
        "plane-manifest", ("plane_manifest.json",),
        ("finalize",),
        "data-plane completion marker (data/plane.py): written "
        "atomically LAST, after every shard sentinel it certifies has "
        "landed — the warm-cache hit test; removed by repair() before "
        "re-landing a corrupt shard so a bad dataset can never keep "
        "its hit marker",
    ),
    ArtifactSpec(
        "plane-delta-lock", (".delta.lock",),
        ("land_delta",),
        "advisory flock target serializing delta landers' whole "
        "seq-allocation -> visibility-record window (data/plane.py): "
        "opened append, never written or read — the lock lives on the "
        "file description, exactly the registry-lock pattern",
        append_ok=True,
    ),
    ArtifactSpec(
        "plane-delta-ok", ("deltaok_",),
        ("land_delta",),
        "row-advance delta visibility record (data/plane.py): written "
        "atomically LAST, after the patch payload landed, the column "
        "memmaps were mutated, and every touched shard sentinel was "
        "re-landed with fresh CRCs — advanced_since() unions only "
        "records that made it here, so a torn delta never half-appears "
        "in a refit claim set",
    ),
    ArtifactSpec(
        "plane-delta-patch", ("deltapatch_",),
        ("land_delta",),
        "row-advance patch payload (data/plane.py): changed rows + the "
        "new trailing-window values, atomic + CRC-stamped FIRST — the "
        "replayable record write_shard re-applies after regenerating a "
        "base shard, so repair after a delta converges to the same "
        "bytes bitwise",
    ),
    ArtifactSpec(
        "refit-plan", ("refit_plan.json",),
        ("_write_refit_plan",),
        "delta-refit cycle plan (tsspark_tpu.refit): base version, "
        "coverage stamps, the pinned changed-row set — replaced "
        "atomically at detect time and again (complete=true) after the "
        "flip, so a successor of a killed cycle resumes the SAME claim "
        "set instead of racing deltas landed after the kill",
    ),
    ArtifactSpec(
        "refit-spill-ok", ("spillok.json",),
        ("ensure_spill",),
        "spill-set visibility marker inside a refit cycle dir "
        "(tsspark_tpu.refit): each gathered spill column is "
        "individually atomic but the SET is not — the marker, written "
        "atomically LAST, is what lets a resumed (or pipelined-"
        "prefetched) cycle trust the gather instead of re-spilling "
        "against half a column set",
    ),
    ArtifactSpec(
        "refit-cold-meta", ("cold_meta.json",),
        ("save_cold_meta",),
        "reusable cold-reference record (bench --delta/--freshness "
        "--reuse-cold): the measured cold fit+publish walls plus the "
        "shape/data-fingerprint identity that gates reuse; written "
        "once atomically after the measurement, ignored whole when "
        "stale",
    ),
    ArtifactSpec(
        "sched-state", ("sched_state.json",),
        ("RefitScheduler._write_sched_state",),
        "always-on scheduler telemetry (tsspark_tpu.sched): cycle "
        "counts, freshness summary, backoff state — replaced "
        "atomically after every cycle so obs watch never parses a "
        "torn record.  ADVISORY only: crash-recovery correctness "
        "rides the refit-plan protocol, and a successor scheduler "
        "tolerates this file missing entirely",
    ),
    ArtifactSpec(
        "freshness-bench-report", ("BENCH_freshness_",),
        ("_write_freshness_report",),
        "freshness-stream report (bench --freshness; "
        "tsspark_tpu.sched): steady-state data-to-forecast freshness "
        "p50/p95 under a sustained churn stream, one artifact per "
        "loop mode (serialized/pipelined), written once atomically "
        "and judged by the regression sentinel under "
        "[tool.tsspark.slo.freshness]",
    ),
    ArtifactSpec(
        "alerts-bench-report", ("BENCH_alerts_",),
        ("_write_alerts_report",),
        "alert-stream report (bench --alerts; tsspark_tpu.alerts."
        "bench): land->alert-ack freshness p50/p95 under a churn "
        "stream, written once atomically and judged by the regression "
        "sentinel under [tool.tsspark.slo.alerts]",
    ),
    ArtifactSpec(
        "alerts-spec", ("alerts_spec.json",),
        ("AlertStream._ensure_spec",),
        "alert-log identity record (alerts/stream.py): dataset/"
        "horizon/quantiles/sink — the spec-FIRST step of the alert-"
        "record protocol, written once atomically before any record",
    ),
    ArtifactSpec(
        "alert-record", ("alertrec_",),
        ("AlertStream.score_seq",),
        "one delta's canonical alert record (alerts/stream.py): the "
        "deterministic scorer's output bytes, landed atomically; "
        "UNREADABLE until its alertok_ sentinel certifies the CRC — a "
        "killed scorer leaves it unscored and the successor's "
        "re-score converges bitwise",
    ),
    ArtifactSpec(
        "alert-record-ok", ("alertok_",),
        ("AlertStream.score_seq",),
        "CRC sentinel certifying one alert record's canonical bytes "
        "(the sentinel-LAST step): readers treat a missing/mismatched "
        "sentinel as not-scored, never as empty",
    ),
    ArtifactSpec(
        "alert-watermark", ("alerts_watermark.json",),
        ("AlertStream._advance_watermark",),
        "delivery watermark (alerts/stream.py): highest seq whose "
        "alerts the sink has ALL acked, replaced atomically only "
        "after the acks; a torn/absent watermark reads as 0 and the "
        "keyed dedup makes redelivery harmless — fast-forward "
        "pointer, never a correctness input",
    ),
    ArtifactSpec(
        "alert-sink-queue", ("alerts_queue.jsonl",),
        ("AlertStream.deliver_loose", "AlertStream._rewrite_queue"),
        "durable overflow queue for loose alerts an open sink breaker "
        "refused (alerts/stream.py): appended per refused alert, "
        "drained with keyed dedup on recovery, rewritten atomically — "
        "alerts are never dropped, only parked here",
        append_ok=True,
    ),
    ArtifactSpec(
        "alert-sink", (),
        ("JsonlSink.emit", "JsonlSink.recover"),
        "the JSONL delivery sink (alerts/sink.py): one alert per line "
        "through the durable append path at a caller-supplied path; "
        "readers tolerate a torn last line and recover() terminates "
        "it so later appends never concatenate",
        append_ok=True,
    ),
    ArtifactSpec(
        "delta-bench-report", ("BENCH_delta_",),
        ("run_delta_bench",),
        "delta-refit churn-sweep report (bench --delta): one "
        "bench-family artifact per (rung, churn) stamping "
        "delta_series_per_s / delta_wall_frac, written once atomically "
        "and ingested through the regression sentinel under a "
        "+delta<churn> workload key",
    ),
    ArtifactSpec(
        "plane-shard-ok", ("shardok_",),
        ("write_shard", "import_batch", "_land_shard_sentinel",
         "_reland_sentinel_from_disk"),
        "per-shard visibility sentinel (data/plane.py): atomic write "
        "AFTER the shard's memmap rows are flushed, payload CRCs "
        "inside; readers trust only sentinel-covered rows, so a torn "
        "shard is never consumed; concurrent producers write identical "
        "bytes (block-seeded determinism) and the last rename wins "
        "whole",
    ),
    ArtifactSpec(
        "plane-spec", ("spec.json",),
        ("create_columns", "import_batch"),
        "dataset identity record (data/plane.py): generator/shape/seed/"
        "shard width/datagen fingerprint, written atomically once at "
        "dataset creation, read-only thereafter (its presence marks a "
        "dir as plane-managed for ready_coverage gating)",
    ),
    ArtifactSpec(
        "ingest-report", ("ingest_report.json",),
        ("run_ingest",),
        "ingest overlap accounting (data/ingest.py): wall/first-shard/"
        "last-shard seconds, written atomically once at ingest end; "
        "pure diagnostics folded into BENCH extras",
    ),
    ArtifactSpec(
        "delta-manifest", ("delta_manifest.json",),
        ("write_plane_delta",),
        "delta-publish metadata (serve/snapplane.py): base version, "
        "the changed row/id set, the data-plane coverage stamp — "
        "written atomically after the new version's sentinel (pure "
        "metadata: the registry manifest referencing the version dir "
        "is the real visibility gate); the serving side reads it to "
        "carry unchanged series' cache entries forward across a delta "
        "flip.  Must precede the registry-manifest spec: its filename "
        "contains the 'manifest.json' fragment",
    ),
    ArtifactSpec(
        # The unified plane library's generic writers: their path
        # arguments are caller-supplied (no literal fragment), so they
        # classify by writer name.  Every plane artifact they produce
        # also has its own marker-keyed spec above/below carrying the
        # per-family lifecycle story.
        "plane-protocol", (),
        ("write_spec", "write_column", "write_sentinel",
         "publish_plane"),
        "generic column-plane protocol writers (plane/protocol.py), "
        "each an atomic publish through tsspark_tpu.io: spec first, "
        "column payloads, CRC sentinel LAST — the one implementation "
        "the plane-protocol ProtocolSpec verifies for every caller",
    ),
    ArtifactSpec(
        "snapshot-plane", ("snapcol_", "snap_spec.json", "snapok.json"),
        ("write_plane", "write_plane_delta", "publish_plane",
         "write_spec", "write_column", "write_sentinel",
         "link_or_copy"),
        "mmap snapshot column plane (serve/snapplane.py): spec first, "
        "one atomic .npy per FitState column + the id->row index, the "
        "per-shard CRC sentinel LAST — the unit of visibility, exactly "
        "the data plane's protocol.  The version dir is "
        "publisher-private until the registry manifest references it, "
        "so a publisher killed mid-plane leaves an orphan dir the "
        "version allocator skips; readers attach mmap and REJECT any "
        "plane whose sentinel CRCs mismatch (fallback: the archival "
        "npz, then the active->previous chain)",
    ),
    ArtifactSpec(
        "forecast-plane",
        ("fcol_", "fplane_spec.json", "fplaneok.json"),
        ("write_plane", "write_plane_delta", "write_spec",
         "write_column", "write_sentinel", "link_or_copy"),
        "materialized forecast plane (serve/fplane.py): the active "
        "version's full (series x horizon-bucket) point-forecast table "
        "as mmap columns — spec first, one atomic .npy per (bucket, "
        "output key), CRC sentinel LAST, the snapshot plane's exact "
        "protocol.  Torn publishes fail the sentinel and attach() "
        "REJECTS them; the engine then serves through its compute path "
        "(never a wrong number, never an outage) and a retry publishes "
        "bitwise-identical bytes.  Delta versions hardlink/copy-forward "
        "unchanged series' columns like snapplane",
    ),
    ArtifactSpec(
        "aot-bank", ("aot_bank.json",),
        ("build_bank",),
        "AOT program-bank manifest (serve/aotbank.py): the (width, "
        "horizon-bucket) ladder pre-compiled into the shared JAX "
        "persistent compilation cache at publish time, written "
        "atomically AFTER every entry compiled; pure idempotency "
        "record — a stale or missing manifest just means replicas "
        "compile as before (the executables live in the cache's own "
        "content-addressed files)",
    ),
    ArtifactSpec(
        "serveplane-bench-report", ("BENCH_serveplane_",),
        ("run_serveplane_bench",),
        "forecast-plane serve benchmark (bench --serveplane; "
        "serve/planebench.py): plane-vs-dispatch hot-read throughput, "
        "plane publish wall, replica TTFR cold vs AOT-warm — written "
        "once atomically and judged by the regression sentinel under "
        "[tool.tsspark.slo.serve] plane budgets",
    ),
    ArtifactSpec(
        "scale-report", ("SCALE_",),
        ("_write_scale_report",),
        "scale-ladder rung report (tsspark_tpu.bench_scale): ingest/"
        "fit/publish/serve timings + sharing-aware RSS accounting, "
        "written once at rung end, atomic so a watcher never parses a "
        "partial JSON; ingested into RUNHISTORY under scale_<rung> "
        "workload keys",
    ),
    ArtifactSpec(
        "registry-manifest", ("manifest.json",),
        ("ParamRegistry._write_manifest",),
        "versioned serve-registry index (serve/registry.py), replaced "
        "atomically AFTER the snapshot files it references have landed; "
        "readers (ParamRegistry.load, a concurrent serving daemon) see "
        "the old or the new version set, never a torn index or a "
        "dangling reference",
    ),
    ArtifactSpec(
        "registry-lock", (".manifest.lock",),
        ("ParamRegistry._locked",),
        "advisory flock target serializing registry manifest "
        "read-modify-writes (publish/activate); opened append, never "
        "written or read — the lock lives on the file description",
        append_ok=True,
    ),
    ArtifactSpec(
        "serve-report", ("SERVE_",),
        ("_write_report",),
        "serve loadgen latency report, written once at end of run "
        "(the serving analog of a BENCH summary); atomic so a watcher "
        "tailing for the artifact never parses a partial JSON",
    ),
    ArtifactSpec(
        "pool-state", ("pool.json",),
        ("ReplicaPool._write_state",),
        "replica-pool front state (serve/pool.py): slot -> socket/pid/"
        "generation map, replaced atomically on every (re)spawn and "
        "activation so a successor front (ReplicaPool.attach, the "
        "front-crash recovery path) never parses a torn index; the "
        "slot LEASES — not this file — arbitrate process ownership",
    ),
    ArtifactSpec(
        "pool-heartbeat", ("poolhb_",),
        ("_Replica.run", "_Replica._heartbeat"),
        "replica liveness mtime (serve/pool.py): created once at "
        "replica start (append-open), utime-touched per heartbeat; the "
        "front reads mtime only — same contract as the fit worker "
        "heartbeat",
        append_ok=True,
    ),
    ArtifactSpec(
        "timing-log", ("times.jsonl",),
        ("fit_worker", "fit_worker.save_and_log", "_times_row"),
        "append-only per-chunk diagnostics (doubles as the perf "
        "telemetry rows bench.py summarizes — docs/PERF.md); the "
        "mesh-resident path appends the same rows via _times_row",
        append_ok=True,
    ),
    ArtifactSpec(
        "autotune-state", ("autotune.json",),
        ("ChunkAutotuner.save",),
        "learned chunk size + per-size throughput samples, written "
        "atomically after every recorded chunk by the fit worker's "
        "tuner; read by resumed workers, bench.py's prep sizing, and "
        "the streaming driver's warm start — pure cache, corrupt "
        "copies ignored at load",
    ),
    ArtifactSpec(
        "probe-log", ("probes.jsonl",),
        ("run_resilient._probe_log",),
        "append-only probe diagnostics", append_ok=True,
    ),
    ArtifactSpec(
        "checkpoint", (".npz", ".json"),
        ("save_state", "save_forecaster"),
        "fitted-state + sidecar pair written via utils.atomic; readers "
        "(load_state/load_forecaster, possibly concurrent processes) "
        "never see a torn file",
    ),
    ArtifactSpec(
        "analysis-report", ("ANALYSIS_",),
        ("write_report",),
        "static-analysis gate result (tsspark_tpu.analysis.report): "
        "findings per checker, waiver counts, wall time — written once "
        "atomically at the end of a CLI gate run and ingested into "
        "RUNHISTORY as the `analysis` row family, so waiver creep and "
        "gate-runtime growth are visible (and sentinel-gateable) on "
        "the trajectory",
    ),
    ArtifactSpec(
        # The durable-I/O layer itself (io/durable.py): its wrappers
        # delegate to each other with caller-supplied paths, so the
        # inner calls classify by writer name.  Each artifact the
        # wrappers ultimately produce is registered at its call site's
        # module via markers.
        "io-layer", (),
        ("atomic_write", "atomic_write_text", "append_line"),
        "the durable-I/O choke point (tsspark_tpu.io): budget gate, "
        "io_* fault points, fsync barrier, classified errors — the "
        "helper every storage-fault-domain artifact routes through",
    ),
    ArtifactSpec(
        "fault-injection", (),
        ("corrupt_file", "FaultPlan.corrupt_file", "inject"),
        "deterministic test-only corruption/sentinels (resilience."
        "faults): in-place byte flips are the FEATURE being tested",
        exempt=True,
    ),
)

# Modules under the package root whose write sites are in protocol scope.
PROTOCOL_MODULES: Tuple[str, ...] = (
    "tsspark_tpu/orchestrate.py",
    "tsspark_tpu/resident.py",
    "tsspark_tpu/refit.py",
    "tsspark_tpu/sched.py",
    "tsspark_tpu/data/plane.py",
    "tsspark_tpu/data/ingest.py",
    "tsspark_tpu/io/durable.py",
    "tsspark_tpu/io/budget.py",
    "tsspark_tpu/io/ladder.py",
    "tsspark_tpu/io/errors.py",
    "tsspark_tpu/plane/protocol.py",
    "tsspark_tpu/streaming/state.py",
    "tsspark_tpu/streaming/driver.py",
    "tsspark_tpu/streaming/source.py",
    "tsspark_tpu/streaming/warmstart.py",
    "tsspark_tpu/utils/checkpoint.py",
    "tsspark_tpu/resilience/integrity.py",
    "tsspark_tpu/resilience/faults.py",
    "tsspark_tpu/perf/autotune.py",
    "tsspark_tpu/perf/recorder.py",
    "tsspark_tpu/serve/registry.py",
    "tsspark_tpu/serve/snapplane.py",
    "tsspark_tpu/serve/fplane.py",
    "tsspark_tpu/serve/aotbank.py",
    "tsspark_tpu/serve/planebench.py",
    "tsspark_tpu/serve/engine.py",
    "tsspark_tpu/serve/cache.py",
    "tsspark_tpu/serve/pool.py",
    "tsspark_tpu/serve/replica.py",
    "tsspark_tpu/serve/__main__.py",
    "tsspark_tpu/bench_scale.py",
    "tsspark_tpu/alerts/stream.py",
    "tsspark_tpu/alerts/sink.py",
    "tsspark_tpu/alerts/bench.py",
    "tsspark_tpu/chaos/storm.py",
    "tsspark_tpu/chaos/harness.py",
    "tsspark_tpu/chaos/invariants.py",
    "tsspark_tpu/chaos/__main__.py",
    "tsspark_tpu/obs/context.py",
    "tsspark_tpu/obs/metrics.py",
    "tsspark_tpu/obs/ledger.py",
    "tsspark_tpu/obs/history.py",
    "tsspark_tpu/obs/regress.py",
    "tsspark_tpu/obs/watch.py",
    "tsspark_tpu/obs/__main__.py",
    "tsspark_tpu/analysis/report.py",
)

_WRITE_FNS = {"save", "savez", "savez_compressed", "dump"}
_ATOMIC_FNS = {"atomic_write", "atomic_write_text"}


@dataclasses.dataclass
class WriteSite:
    relpath: str
    line: int
    qualname: str
    mode: str                  # "w", "wb", "a", ... ("?" when dynamic)
    constants: Tuple[str, ...]  # string constants in the path expression
    in_atomic_fn: bool         # enclosing function contains os.replace
    via_helper: bool           # the call IS atomic_write(...)


def _string_constants(
    node: ast.AST,
    const_map: Optional[Dict[str, str]] = None,
) -> Tuple[str, ...]:
    """String constants in a path expression.  ``const_map`` resolves
    module-level ``NAME = "literal"`` references too, so a write site
    built as ``os.path.join(d, SNAP_SPEC)`` classifies by its marker
    instead of falling through to the writer-name fallback."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
        elif (const_map and isinstance(n, ast.Name)
              and n.id in const_map):
            out.append(const_map[n.id])
    return tuple(out)


def module_str_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments (the artifact
    filename constants every protocol module declares at the top)."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) \
                and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt.value.value
    return out


def _fn_qualname_map(tree: ast.Module):
    """{node-id: qualname} for every function def, nested included."""
    out = {}

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[id(child)] = f"{prefix}{child.name}"
                visit(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _collect_write_sites(relpath: str, source: str) -> List[WriteSite]:
    tree = ast.parse(source, filename=relpath)
    qualnames = _fn_qualname_map(tree)
    mod_consts = module_str_constants(tree)
    sites: List[WriteSite] = []

    def fn_has_replace(fn: ast.AST) -> bool:
        for sub in ast.walk(fn):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "replace"
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "os"):
                return True
        return False

    def visit_fn(fn: ast.AST, qual: str) -> None:
        atomic_fn = fn_has_replace(fn)
        nested = {
            id(sub) for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
            for sub in ast.walk(n)
        }
        for sub in ast.walk(fn):
            if id(sub) in nested or not isinstance(sub, ast.Call):
                continue
            func = sub.func
            # open(path, mode) in a writing mode
            if isinstance(func, ast.Name) and func.id == "open":
                mode = "r"
                if len(sub.args) > 1 and isinstance(sub.args[1],
                                                    ast.Constant):
                    mode = str(sub.args[1].value)
                elif len(sub.args) > 1:
                    mode = "?"
                for kw in sub.keywords:
                    if kw.arg == "mode":
                        mode = (str(kw.value.value)
                                if isinstance(kw.value, ast.Constant)
                                else "?")
                if any(c in mode for c in "wax+?"):
                    sites.append(WriteSite(
                        relpath, sub.lineno, qual, mode,
                        _string_constants(sub.args[0], mod_consts)
                        if sub.args else (),
                        atomic_fn, False,
                    ))
            # np.save/np.savez/json.dump/pickle.dump with a PATH (not an
            # open file handle) — a handle comes from a tracked open()
            elif (isinstance(func, ast.Attribute)
                    and func.attr in _WRITE_FNS and sub.args):
                target = (sub.args[1] if func.attr == "dump"
                          and len(sub.args) > 1 else sub.args[0])
                consts = _string_constants(target, mod_consts)
                # Heuristic: writes to a bare Name with no path-ish
                # constants are almost always file handles from an
                # enclosing open()/atomic_write (already checked).
                pathish = consts or not isinstance(target, ast.Name)
                if pathish:
                    sites.append(WriteSite(
                        relpath, sub.lineno, qual, "wb", consts,
                        atomic_fn, False,
                    ))
            elif (isinstance(func, ast.Name)
                    and func.id in _ATOMIC_FNS):
                sites.append(WriteSite(
                    relpath, sub.lineno, qual, "w",
                    _string_constants(sub.args[0], mod_consts)
                    if sub.args else (),
                    atomic_fn, True,
                ))

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_fn(child, qualnames[id(child)])
                walk(child, f"{qualnames[id(child)]}.")
            elif isinstance(child, ast.ClassDef):
                walk(child, prefix)
            else:
                walk(child, prefix)

    walk(tree, "")
    return sites


def _classify(site: WriteSite) -> Optional[ArtifactSpec]:
    # Most-specific (longest) matching marker wins, so a generic
    # fragment ("spec.json", ".json") never swallows a specific one
    # ("snap_spec.json", "plane_manifest.json"); registry order is the
    # tiebreak.
    best: Optional[ArtifactSpec] = None
    best_len = -1
    for spec in ARTIFACTS:
        for marker in spec.markers:
            if len(marker) > best_len and any(
                marker in const for const in site.constants
            ):
                best, best_len = spec, len(marker)
    if best is not None:
        return best
    # Variable path with no literal fragment: attribute by the writing
    # function itself — the registry maps owners to artifacts, so a
    # registered owner's writes classify even when the path is computed
    # elsewhere (save_chunk_atomic's path comes from _chunk_path).
    for spec in ARTIFACTS:
        if _writer_allowed(spec, site.qualname):
            return spec
    return None


def check_write_sites(
    root: str, modules: Sequence[str] = PROTOCOL_MODULES,
) -> List[Finding]:
    findings: List[Finding] = []
    for rel in modules:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        with open(path, "r") as fh:
            source = fh.read()
        for site in _collect_write_sites(rel, source):
            spec = _classify(site)
            writes = any(c in site.mode for c in "wx+?")
            appends = "a" in site.mode
            if site.via_helper:
                if spec is None:
                    findings.append(Finding(
                        "unregistered-artifact", site.relpath, site.line,
                        site.qualname,
                        "atomic_write to a path matching no registered "
                        f"artifact (constants {site.constants!r}); add "
                        "an ArtifactSpec with its lifecycle",
                    ))
                elif not _writer_allowed(spec, site.qualname):
                    findings.append(Finding(
                        "foreign-writer", site.relpath, site.line,
                        site.qualname,
                        f"{spec.name} is owned by {spec.writers}; a new "
                        "writer needs a registry entry (and a story for "
                        "how it cannot race the owner)",
                    ))
                continue
            if spec is not None and (
                spec.exempt or (spec.append_ok and appends)
            ):
                continue
            if not (writes or appends):
                continue
            if site.in_atomic_fn:
                # Manual temp+os.replace idiom inside this function: the
                # open/np.save is the temp side of an atomic rename.
                continue
            if spec is None:
                if appends:
                    findings.append(Finding(
                        "unregistered-artifact", site.relpath, site.line,
                        site.qualname,
                        "append-mode write to an unregistered path "
                        f"(constants {site.constants!r}); register it "
                        "(append_ok) or route through utils.atomic",
                    ))
                else:
                    findings.append(Finding(
                        "non-atomic-write", site.relpath, site.line,
                        site.qualname,
                        "write outside utils.atomic to an unregistered "
                        f"path (constants {site.constants!r}); a "
                        "concurrent reader can observe a torn file",
                    ))
                continue
            findings.append(Finding(
                "non-atomic-write", site.relpath, site.line,
                site.qualname,
                f"{spec.name} written without the atomic "
                "write-temp-then-rename helper (utils.atomic); "
                f"lifecycle: {spec.lifecycle}",
            ))
    return findings


def _writer_allowed(spec: ArtifactSpec, qualname: str) -> bool:
    return any(
        qualname == w or qualname.endswith("." + w)
        or w.startswith(qualname + ".") or qualname.startswith(w + ".")
        for w in spec.writers
    )


# ---------------------------------------------------------------------------
# storage-fault-domain routing: durable writes go through tsspark_tpu.io
# ---------------------------------------------------------------------------

#: Modules inside the storage fault domain.  Durable artifacts written
#: here must route through ``tsspark_tpu.io`` — the one fault-
#: injectable, budget-gated, error-classified choke point — so a raw
#: publish syscall or a direct ``utils.atomic`` import silently opts a
#: writer out of ENOSPC/EIO chaos coverage and is flagged.
IO_ROUTED_PREFIXES: Tuple[str, ...] = (
    "tsspark_tpu/data/",
    "tsspark_tpu/serve/",
    "tsspark_tpu/plane/",
    "tsspark_tpu/alerts/",
)
IO_ROUTED_MODULES: Tuple[str, ...] = (
    "tsspark_tpu/refit.py",
    "tsspark_tpu/sched.py",
)

#: os-level durable publish primitives the io layer owns.
_RAW_OS_DURABLE = frozenset({"replace", "rename", "link", "write"})


def _in_io_scope(rel: str) -> bool:
    return rel.startswith(IO_ROUTED_PREFIXES) or rel in IO_ROUTED_MODULES


def check_io_routing(
    root: str, modules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """The ``fileproto`` routing rule of the storage fault domain:
    modules under ``data/``, ``serve/``, ``plane/`` plus ``refit`` and
    ``sched`` may not import durable-write helpers from
    ``utils.atomic`` directly, call ``os.replace``/``os.rename``/
    ``os.link``/``os.write``, or ``open()`` a file in a create/write
    mode — every durable write goes through ``tsspark_tpu.io`` so each
    one sits behind the ``io_*`` fault points, typed storage errors,
    and the disk budget.  Append-mode opens stay legal: lock files and
    heartbeats are liveness/serialization primitives, not artifacts.

    ``modules`` overrides the scan set verbatim (the seeded-violation
    fixture test); by default the in-scope PROTOCOL_MODULES are
    scanned."""
    if modules is None:
        scan = [rel for rel in PROTOCOL_MODULES if _in_io_scope(rel)]
    else:
        scan = list(modules)
    findings: List[Finding] = []

    def emit(rel, line, qual, detail):
        findings.append(Finding("io-routing", rel, line, qual, detail))

    for rel in scan:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        with open(path, "r") as fh:
            tree = ast.parse(fh.read(), filename=rel)
        qualnames = _fn_qualname_map(tree)

        # Walk with an explicit function stack so findings carry the
        # enclosing qualname.
        def visit(node, stack):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack = stack + [qualnames[id(node)]]
            qual = stack[-1] if stack else "<module>"
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "tsspark_tpu.utils.atomic":
                durable = sorted(
                    a.name for a in node.names
                    if a.name in ("atomic_write", "atomic_write_text",
                                  "append_line")
                )
                if durable:
                    emit(rel, node.lineno, qual,
                         f"imports {durable} from utils.atomic; "
                         "storage-fault-domain modules must import "
                         "durable writers from tsspark_tpu.io so every "
                         "write sits behind the io_* fault points and "
                         "the disk budget")
            elif isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in _RAW_OS_DURABLE
                        and isinstance(func.value, ast.Name)
                        and func.value.id == "os"):
                    emit(rel, node.lineno, qual,
                         f"raw os.{func.attr}() in the storage fault "
                         "domain; route the publish through "
                         "tsspark_tpu.io (atomic_write / hardlink / "
                         "append_line) so it is fault-injectable and "
                         "error-classified")
                elif isinstance(func, ast.Name) and func.id == "open":
                    mode = ""
                    if len(node.args) > 1 \
                            and isinstance(node.args[1], ast.Constant):
                        mode = str(node.args[1].value)
                    for kw in node.keywords:
                        if kw.arg == "mode" \
                                and isinstance(kw.value, ast.Constant):
                            mode = str(kw.value.value)
                    if any(c in mode for c in "wx+"):
                        emit(rel, node.lineno, qual,
                             f"raw open(..., {mode!r}) in the storage "
                             "fault domain; durable artifacts are "
                             "published via tsspark_tpu.io.atomic_write "
                             "(append-mode locks/heartbeats are exempt)")
            for child in ast.iter_child_nodes(node):
                visit(child, stack)

        visit(tree, [])
    return findings


# ---------------------------------------------------------------------------
# range-claim small-model check
# ---------------------------------------------------------------------------


def _overlap(a: Tuple[int, int], b: Tuple[int, int]) -> bool:
    return a[0] < b[1] and b[0] < a[1]


def _claim_violations(
    plan_fn: Callable, done: List[Tuple[int, int]],
    lo: int, hi: int, chunk: int,
) -> List[str]:
    claims = plan_fn(done, lo, hi, chunk)
    errs = []
    for i, c in enumerate(claims):
        if not (lo <= c[0] < c[1] <= hi):
            errs.append(f"claim {c} escapes the worker window "
                        f"[{lo}, {hi}) (done={done}, chunk={chunk})")
        for d in done:
            if _overlap(c, d):
                errs.append(
                    f"claim {c} overlaps completed coverage {d} "
                    f"(done={done}, chunk={chunk}): the refit would "
                    "write an overlapping chunk file and "
                    "load_fit_state would duplicate rows"
                )
        for c2 in claims[i + 1:]:
            if _overlap(c, c2):
                errs.append(f"claims {c} and {c2} overlap "
                            f"(done={done}, chunk={chunk})")
    return errs


def check_claim_invariants(
    plan_fn: Optional[Callable] = None,
    missing_fn: Optional[Callable] = None,
) -> List[Finding]:
    """Exhaustive small-model check of the range-claim protocol.

    States: every completed-coverage set reachable by the protocol over
    a small series count (non-overlapping sub-ranges, including
    bisection singles and stale wider-grid survivors), crossed with the
    worker-window and chunk-size moves the parent actually makes
    (full window, split windows, halved chunks).  Small counterexamples
    find real protocol bugs long before a million-series run does.
    """
    from tsspark_tpu import orchestrate

    plan_fn = plan_fn or orchestrate.plan_chunks
    missing_fn = missing_fn or orchestrate.missing_ranges
    findings: List[Finding] = []

    def emit(msg: str) -> None:
        findings.append(Finding(
            "claim-overlap", "tsspark_tpu/orchestrate.py", 0,
            "plan_chunks", msg,
        ))

    series = 6
    bounds = range(series + 1)
    all_ranges = [
        (a, b) for a, b in itertools.product(bounds, bounds) if a < b
    ]
    # Every pairwise-disjoint coverage set of size <= 3 (the protocol
    # never writes overlapping files — that is the invariant being
    # preserved inductively, so states assume it).
    states: List[List[Tuple[int, int]]] = [[]]
    for k in (1, 2, 3):
        for combo in itertools.combinations(all_ranges, k):
            if all(not _overlap(a, b)
                   for a, b in itertools.combinations(combo, 2)):
                states.append(list(combo))
    seen_err: Set[str] = set()
    for done in states:
        for chunk in (1, 2, 3, 4, 8):
            for lo, hi in ((0, series), (0, 3), (3, series), (2, 5)):
                for msg in _claim_violations(plan_fn, done, lo, hi,
                                             chunk):
                    if msg not in seen_err:
                        seen_err.add(msg)
                        emit(msg)
        # The parent's full-window gap scan and the claim walk must
        # agree: claims exactly tile the missing coverage when the
        # window spans everything.
        claims = plan_fn(done, 0, series, 2)
        claimed = sorted(claims)
        gaps = missing_fn(done, series)
        covered = []
        cur: Optional[Tuple[int, int]] = None
        for c in claimed:
            if cur is not None and c[0] == cur[1]:
                cur = (cur[0], c[1])
            else:
                if cur is not None:
                    covered.append(cur)
                cur = c
        if cur is not None:
            covered.append(cur)
        if covered != list(gaps):
            emit(
                f"claims {claims} do not tile the missing coverage "
                f"{gaps} for done={done}: a worker would leave holes "
                "or refit finished rows"
            )
    # Two workers handed disjoint windows must claim disjoint ranges.
    for done in states[:64]:
        mid = 3
        a = plan_fn(done, 0, mid, 2)
        b = plan_fn(done, mid, series, 2)
        for ca in a:
            for cb in b:
                if _overlap(ca, cb):
                    emit(
                        f"split-window workers claim overlapping ranges "
                        f"{ca} / {cb} for done={done}"
                    )
    return findings


def check_completed_ranges_order() -> List[Finding]:
    """The 999,999-series digit rollover: completed_ranges must sort
    numerically, never lexicographically (chunk_1000448 < chunk_999936
    as strings), checked with real files."""
    from tsspark_tpu import orchestrate

    findings: List[Finding] = []
    ranges = [(999_936, 1_000_448), (0, 512), (1_000_448, 1_000_960),
              (512, 999_936)]
    with tempfile.TemporaryDirectory() as td:
        for lo, hi in ranges:
            with open(
                os.path.join(td, f"chunk_{lo:06d}_{hi:06d}.npz"), "wb"
            ):
                pass
        got = orchestrate.completed_ranges(td)
    if got != sorted(ranges):
        findings.append(Finding(
            "claim-order", "tsspark_tpu/orchestrate.py", 0,
            "completed_ranges",
            f"chunk files sort as {got}, not numerically "
            f"{sorted(ranges)}: past 999,999 series load_fit_state "
            "would concatenate chunks out of order",
        ))
    return findings


def check_fileproto(root: str) -> List[Finding]:
    return (
        check_write_sites(root)
        + check_io_routing(root)
        + check_claim_invariants()
        + check_completed_ranges_order()
    )
