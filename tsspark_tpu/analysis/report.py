"""ANALYSIS_* gate artifact: the static-analysis result as a run row.

Every other gate in this repo leaves a judged artifact on the
trajectory (BENCH/SERVE/CHAOS/...); the analysis gate did not — so
waiver creep and gate-runtime growth were invisible between PRs.  The
CLI (``python -m tsspark_tpu.analysis``) writes one
``ANALYSIS_<unix>.json`` per full run: findings per checker, kept vs
baselined counts, inline + baseline waiver counts, wall time — atomic
(a watcher never parses a torn JSON; the ``analysis-report``
ArtifactSpec in ``fileproto`` owns the lifecycle) and self-ingested
into ``RUNHISTORY.jsonl`` as the ``analysis`` row family, so the
regression sentinel machinery can budget waiver growth like any other
metric.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict

from tsspark_tpu.utils.atomic import atomic_write

# THE inline-waiver pattern — imported, not copied: the counted waiver
# surface must never drift from the surface the checkers honor.
from tsspark_tpu.analysis.tracelint import _INLINE_OK


def count_inline_waivers(package_dir: str) -> Dict[str, int]:
    """``{rule: count}`` of inline ``# lint-ok[rule]: reason`` waivers
    under ``package_dir`` — the other half of the suppression surface
    (the pyproject baseline is the committed half).  Counted over
    COMMENT tokens only: a docstring *mentioning* the marker syntax is
    documentation, not a waiver, and must not move the waiver-creep
    metric this count feeds."""
    import io
    import tokenize

    out: Dict[str, int] = {}
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, fn), "r") as fh:
                    source = fh.read()
                tokens = tokenize.generate_tokens(
                    io.StringIO(source).readline
                )
                for tok in tokens:
                    if tok.type != tokenize.COMMENT:
                        continue
                    m = _INLINE_OK.search(tok.string)
                    if m:
                        rule = m.group("rule")
                        out[rule] = out.get(rule, 0) + 1
            except (OSError, tokenize.TokenError, SyntaxError,
                    IndentationError):
                continue
    return out


def build_report(analysis_report, settings, root: str,
                 wall_s: float) -> Dict[str, Any]:
    """The artifact dict for one FULL gate run (the CLI never writes
    one for --changed/partial runs — their counts are not comparable
    trajectory points)."""
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.history import git_rev

    inline = count_inline_waivers(os.path.join(root, "tsspark_tpu"))
    return {
        "kind": "analysis-gate",
        "unix": round(time.time(), 3),
        "trace_id": obs.trace_id(),
        "git_rev": git_rev(root),
        "wall_s": round(wall_s, 3),
        "ok": analysis_report.ok,
        "findings": len(analysis_report.findings),
        "suppressed": len(analysis_report.suppressed),
        "checkers": {name: n for name, n in analysis_report.counts},
        "waivers_inline": sum(inline.values()),
        "waivers_inline_by_rule": dict(sorted(inline.items())),
        "waivers_baseline": len(settings.suppressions),
    }


def write_report(rep: Dict[str, Any],
                 out_dir: str = ".") -> str:
    """Write the artifact atomically; returns its path."""
    path = os.path.join(out_dir, f"ANALYSIS_{int(rep['unix'])}.json")
    atomic_write(path, lambda fh: json.dump(rep, fh, indent=1),
                 mode="w")
    return path


def ingest_report(rep: Dict[str, Any], path: str,
                  root: str = ".") -> bool:
    """Self-ingest into RUNHISTORY (idempotent by trace id); never
    raises — the gate's exit code must reflect findings, not the
    trajectory plumbing."""
    try:
        from tsspark_tpu.obs import history

        _row, appended = history.ingest(
            rep, os.path.join(root, history.HISTORY_FILE), source=path
        )
        return appended
    except Exception:
        return False
