"""``python -m tsspark_tpu.analysis`` — run the static-analysis gate.

Exit code 0 when every checker is clean (after the committed
suppression baseline), 1 otherwise.  ``--checker`` narrows to one pass;
``-v`` also prints what the baseline suppressed.  ``--changed
<git-ref>`` is the pre-commit fast path: the per-file passes (trace,
concur, the effects checker's per-site rules) run only over package
modules touched since the ref, while the whole-repo models (contracts,
fileproto, proto, hygiene, the effect path budgets and EnvSpec table)
keep their full closure.  A full run writes an ``ANALYSIS_*.json`` artifact and
self-ingests it into RUNHISTORY (``--no-report`` skips both).

The contract checker needs a JAX backend with enough devices for the
mesh matrix: like the test suite's conftest, this entry point pins
JAX to CPU with 8 virtual devices BEFORE jax initializes — the gate
must never touch (or wait on) a real TPU tunnel.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def changed_package_paths(root: str, ref: str):
    """Package ``.py`` files touched since ``ref`` — tracked changes
    PLUS untracked new files (``git diff`` never lists those, and
    brand-new modules are exactly where fresh violations live).
    Absolute paths; deleted files excluded.  Raises on a bad ref — a
    typo'd ref silently scoping to nothing would pass vacuously."""
    out = subprocess.run(
        ["git", "diff", "--name-only", ref, "--", "tsspark_tpu"],
        cwd=root, capture_output=True, text=True, timeout=30,
    )
    if out.returncode != 0:
        raise SystemExit(
            f"--changed {ref!r}: git diff failed: "
            f"{out.stderr.strip() or out.stdout.strip()}"
        )
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard", "--",
         "tsspark_tpu"],
        cwd=root, capture_output=True, text=True, timeout=30,
    )
    if untracked.returncode != 0:
        # Same policy as a failed diff: silently dropping untracked
        # modules would let a brand-new file's violations pass the
        # scoped gate unseen.
        raise SystemExit(
            f"--changed {ref!r}: git ls-files failed: "
            f"{untracked.stderr.strip() or untracked.stdout.strip()}"
        )
    listed = out.stdout.splitlines() + untracked.stdout.splitlines()
    paths = []
    for rel in listed:
        rel = rel.strip()
        if not rel.endswith(".py"):
            continue
        path = os.path.join(root, rel)
        if os.path.exists(path) and path not in paths:
            paths.append(path)
    return paths


def main(argv=None) -> int:
    # Must precede any jax import anywhere in the process.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tsspark_tpu.resident import force_virtual_host_mesh

    force_virtual_host_mesh()

    ap = argparse.ArgumentParser(
        prog="python -m tsspark_tpu.analysis",
        description="JAX/TPU-aware static analysis (docs/ANALYSIS.md)",
    )
    ap.add_argument(
        "--checker",
        choices=("trace", "contracts", "fileproto", "concur", "proto",
                 "hygiene", "effects"),
        action="append",
        help="run only this checker (repeatable; default: all)",
    )
    ap.add_argument("--root", default=None,
                    help="repo root (default: the package's parent)")
    ap.add_argument("--changed", default=None, metavar="GIT_REF",
                    help="fast mode: scope the per-file passes (trace, "
                         "concur, effects site rules) to package "
                         "modules touched since this ref (contracts/"
                         "fileproto/proto/hygiene and the effect path "
                         "budgets still run whole)")
    ap.add_argument("--no-report", action="store_true",
                    help="skip the ANALYSIS_* artifact + RUNHISTORY "
                         "ingest (fast/scoped runs skip it anyway)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print baseline-suppressed findings")
    args = ap.parse_args(argv)

    from tsspark_tpu import analysis

    checkers = (tuple(args.checker) if args.checker
                else analysis.DEFAULT_CHECKERS)

    # The machine image may pre-register a TPU plugin at interpreter
    # start; pin the config level too (same defense as tests/conftest).
    if "contracts" in checkers:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from tsspark_tpu.analysis.config import repo_root

    root = args.root or repo_root()
    scope = None
    if args.changed:
        scope = changed_package_paths(root, args.changed)
        if not scope:
            print(f"--changed {args.changed}: no package modules "
                  "touched; per-file passes are vacuous")

    t0 = time.monotonic()
    from tsspark_tpu.analysis.config import load_settings

    settings = load_settings(root)
    report = analysis.run_all(
        root=root, settings=settings, checkers=checkers,
        scope_paths=scope,
    )
    wall_s = time.monotonic() - t0
    for f in report.findings:
        print(f)
    if args.verbose:
        for f in report.suppressed:
            print(f"[suppressed] {f}")
    per = ", ".join(f"{name}: {n}" for name, n in report.counts)
    kept = len(report.findings)
    print(
        f"tsspark_tpu.analysis: {kept} finding(s) "
        f"({len(report.suppressed)} baselined; raw per checker: {per})"
    )
    # The artifact records FULL gate runs only: a scoped/partial run's
    # counts are not comparable points on the trajectory.
    if (not args.no_report and scope is None
            and set(checkers) == set(analysis.DEFAULT_CHECKERS)):
        from tsspark_tpu.analysis import report as report_mod

        rep = report_mod.build_report(report, settings, root, wall_s)
        path = report_mod.write_report(rep, out_dir=root)
        ingested = report_mod.ingest_report(rep, path, root=root)
        print(f"report: {os.path.basename(path)}"
              f"{' (ingested)' if ingested else ''}")
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
