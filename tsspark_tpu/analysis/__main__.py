"""``python -m tsspark_tpu.analysis`` — run the static-analysis gate.

Exit code 0 when every checker is clean (after the committed
suppression baseline), 1 otherwise.  ``--checker`` narrows to one pass;
``-v`` also prints what the baseline suppressed.

The contract checker needs a JAX backend with enough devices for the
mesh matrix: like the test suite's conftest, this entry point pins
JAX to CPU with 8 virtual devices BEFORE jax initializes — the gate
must never touch (or wait on) a real TPU tunnel.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    # Must precede any jax import anywhere in the process.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tsspark_tpu.resident import force_virtual_host_mesh

    force_virtual_host_mesh()

    ap = argparse.ArgumentParser(
        prog="python -m tsspark_tpu.analysis",
        description="JAX/TPU-aware static analysis (docs/ANALYSIS.md)",
    )
    ap.add_argument(
        "--checker",
        choices=("trace", "contracts", "fileproto", "hygiene"),
        action="append",
        help="run only this checker (repeatable; default: all)",
    )
    ap.add_argument("--root", default=None,
                    help="repo root (default: the package's parent)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print baseline-suppressed findings")
    args = ap.parse_args(argv)

    from tsspark_tpu import analysis

    # The machine image may pre-register a TPU plugin at interpreter
    # start; pin the config level too (same defense as tests/conftest).
    if any("contracts" in c for c in (args.checker or ["contracts"])):
        import jax

        jax.config.update("jax_platforms", "cpu")

    report = analysis.run_all(
        root=args.root,
        checkers=tuple(args.checker) if args.checker
        else ("trace", "contracts", "fileproto", "hygiene"),
    )
    for f in report.findings:
        print(f)
    if args.verbose:
        for f in report.suppressed:
            print(f"[suppressed] {f}")
    per = ", ".join(f"{name}: {n}" for name, n in report.counts)
    kept = len(report.findings)
    print(
        f"tsspark_tpu.analysis: {kept} finding(s) "
        f"({len(report.suppressed)} baselined; raw per checker: {per})"
    )
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
