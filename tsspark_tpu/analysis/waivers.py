"""Stale-waiver detection: waivers must die with the code they excuse.

Both waiver mechanisms — the inline ``# lint-ok[rule]: reason`` comment
and the pyproject suppression baseline — are REVIEWED exceptions.  An
exception that no longer suppresses anything is worse than dead code:
it reads as "this risk is acknowledged here" while the risk has moved
or vanished, and it will silently excuse the NEXT finding that happens
to land on its line.  So on every full gate pass, a waiver matching no
finding is itself a ``stale-waiver`` finding.

Mechanism: ``tracelint._ModuleScan.line_ok`` — the single choke point
through which trace, concur, AND effects consult inline waivers — now
records every (relpath, line, rule) it actually matched into
``tracelint.WAIVER_HITS``.  ``line_ok`` is only ever called at the
moment a finding is about to be emitted, so consumed == suppressed a
real finding; after a full pass, every tokenizer-discovered waiver
site absent from the hit set is stale.  Baseline entries are simpler:
``apply_suppressions`` already returns the findings each key absorbed,
so a key absorbing zero is stale.

The check only runs on FULL passes (no ``--changed`` scope, all
default checkers): on a scoped run most waivers legitimately go
unconsulted, and flagging them would teach operators to ignore the
rule.  Like every other rule, ``stale-waiver`` findings can themselves
be baseline-suppressed (e.g. a waiver kept deliberately across a
refactor window) — but not inline-waived, which would be turtles all
the way down.
"""

from __future__ import annotations

import io
import os
import tokenize
from typing import Iterable, List, Set, Tuple

from tsspark_tpu.analysis import tracelint
from tsspark_tpu.analysis.findings import Finding


def inline_waiver_sites(package_dir: str,
                        root: str) -> List[Tuple[str, int, str]]:
    """Every ``# lint-ok[rule]:`` comment in the package as (relpath,
    line, rule), via the tokenizer (same discipline as the report's
    waiver census: comments only, no string-literal false hits)."""
    sites: List[Tuple[str, int, str]] = []
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            relpath = os.path.relpath(path, root)
            try:
                with open(path, "r") as fh:
                    source = fh.read()
                tokens = tokenize.generate_tokens(
                    io.StringIO(source).readline
                )
                for tok in tokens:
                    if tok.type != tokenize.COMMENT:
                        continue
                    m = tracelint._INLINE_OK.search(tok.string)
                    if m:
                        sites.append(
                            (relpath, tok.start[0], m.group("rule"))
                        )
            except (OSError, tokenize.TokenizeError, SyntaxError):
                continue
    return sites


def check_stale(
    package_dir: str,
    root: str,
    consumed_inline: Set[Tuple[str, int, str]],
    suppression_keys: Iterable[Tuple[str, str, str]],
    raw_findings: Iterable[Finding],
) -> List[Finding]:
    """``stale-waiver`` findings for (a) inline waiver sites that
    suppressed nothing this pass, (b) baseline suppression keys that
    matched zero raw findings.  Call AFTER all checkers ran so
    ``consumed_inline`` (normally ``tracelint.WAIVER_HITS``) is
    complete."""
    findings: List[Finding] = []
    for relpath, line, rule in sorted(inline_waiver_sites(package_dir,
                                                          root)):
        if (relpath, line, rule) not in consumed_inline:
            findings.append(Finding(
                "stale-waiver", relpath, line, "<inline>",
                f"lint-ok[{rule}] waiver suppressed no finding this "
                "pass — waivers must die with the code they excuse",
            ))
    matched = {(f.rule, f.path, f.qualname) for f in raw_findings}
    keys = list(suppression_keys)

    def flag(rule: str, relpath: str, qualname: str) -> None:
        findings.append(Finding(
            "stale-waiver", relpath, 0, qualname,
            f"baseline suppression for {rule!r} matches no finding — "
            "remove the entry from [tool.tsspark.analysis] "
            "suppressions",
        ))

    # Ordinary keys first; keys suppressing stale-waiver findings are
    # judged against the stale findings built just above (a baseline
    # entry keeping a known-stale waiver alive across a refactor
    # window is consumed by the very finding it absorbs).
    for rule, relpath, qualname in keys:
        if rule != "stale-waiver" \
                and (rule, relpath, qualname) not in matched:
            flag(rule, relpath, qualname)
    stale_keys = {(f.rule, f.path, f.qualname) for f in findings}
    for rule, relpath, qualname in keys:
        if rule == "stale-waiver" \
                and (rule, relpath, qualname) not in stale_keys:
            flag(rule, relpath, qualname)
    return findings
