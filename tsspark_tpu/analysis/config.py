"""Settings for the static-analysis pass: ``[tool.tsspark.analysis]``.

The analysis subsystem is configured from the repo's ``pyproject.toml``
so the suppression baseline and the kernel-contract shape matrix are
COMMITTED artifacts reviewed like code — a PR that needs a new
suppression shows it in the diff.

Suppression entry format (one string per finding)::

    "<rule> @ <relpath>::<qualname> -- <justification>"

e.g. ``"host-sync @ tsspark_tpu/models/prophet/model.py::select_better_state
-- selection runs host-side between dispatches"``.  A suppression
matches every finding of that rule inside that function (line numbers
churn; rule+symbol identity does not).  The justification is MANDATORY
— a baseline entry is a reviewed exception, and an exception without
its reason is indistinguishable from a rubber stamp; entries missing
the `` -- `` clause raise at load.  Inline suppressions use a
``# lint-ok[<rule>]: <reason>`` comment on the flagged line; the reason
is mandatory there too — a bare marker does not count.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class KernelMatrix:
    """The shape grid the contract checker drives ``jax.eval_shape`` over.

    Every combination of (batch, length) x (n_changepoints, regressors)
    is checked on the single-device kernels; ``mesh_shapes`` adds
    (series_shards, time_shards) layouts for the sharded programs —
    batches/lengths must stay divisible by the widest mesh axes.
    """

    batch_sizes: Tuple[int, ...] = (8, 32)
    lengths: Tuple[int, ...] = (64, 256)
    n_changepoints: Tuple[int, ...] = (0, 4)
    num_regressors: Tuple[int, ...] = (0, 2)
    mesh_shapes: Tuple[Tuple[int, int], ...] = ((8, 1), (4, 2))


@dataclasses.dataclass(frozen=True)
class AnalysisSettings:
    suppressions: Tuple[str, ...] = ()
    kernel_matrix: KernelMatrix = KernelMatrix()

    def suppression_keys(self) -> Tuple[Tuple[str, str, str], ...]:
        """Parsed (rule, relpath, qualname) triples; malformed entries
        raise (a typo'd suppression silently matching nothing would
        quietly re-open the finding it was meant to justify), and so
        does a missing ``-- justification`` clause — every baseline
        waiver must carry its reason in the committed diff."""
        out = []
        for s in self.suppressions:
            body, sep, justification = s.partition(" -- ")
            if not sep or not justification.strip():
                raise ValueError(
                    f"analysis suppression {s!r} carries no "
                    "justification; expected '<rule> @ <relpath>::"
                    "<qualname> -- <why this exception is sound>'"
                )
            try:
                rule, rest = body.split("@", 1)
                relpath, qualname = rest.strip().split("::", 1)
            except ValueError:
                raise ValueError(
                    f"malformed analysis suppression {s!r}; expected "
                    "'<rule> @ <relpath>::<qualname> -- <justification>'"
                )
            out.append((rule.strip(), relpath.strip(), qualname.strip()))
        return tuple(out)


def repo_root() -> str:
    """The directory holding ``pyproject.toml`` — the package's parent."""
    import tsspark_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        tsspark_tpu.__file__
    )))


def _load_toml(path: str) -> Dict:
    try:
        import tomllib as toml_mod  # Python >= 3.11
    except ModuleNotFoundError:
        import tomli as toml_mod
    with open(path, "rb") as fh:
        return toml_mod.load(fh)


def load_settings(root: Optional[str] = None) -> AnalysisSettings:
    """AnalysisSettings from ``<root>/pyproject.toml`` (defaults when the
    file or the ``[tool.tsspark.analysis]`` block is absent, so the
    analysis also runs on an installed wheel without its repo)."""
    root = root or repo_root()
    path = os.path.join(root, "pyproject.toml")
    if not os.path.exists(path):
        return AnalysisSettings()
    block = (
        _load_toml(path).get("tool", {}).get("tsspark", {})
        .get("analysis", {})
    )
    km = block.get("kernel_matrix", {})
    matrix = KernelMatrix(
        batch_sizes=tuple(km.get("batch_sizes",
                                 KernelMatrix.batch_sizes)),
        lengths=tuple(km.get("lengths", KernelMatrix.lengths)),
        n_changepoints=tuple(km.get("n_changepoints",
                                    KernelMatrix.n_changepoints)),
        num_regressors=tuple(km.get("num_regressors",
                                    KernelMatrix.num_regressors)),
        mesh_shapes=tuple(
            tuple(m) for m in km.get("mesh_shapes",
                                     KernelMatrix.mesh_shapes)
        ),
    )
    settings = AnalysisSettings(
        suppressions=tuple(block.get("suppressions", ())),
        kernel_matrix=matrix,
    )
    settings.suppression_keys()  # validate eagerly
    return settings
