"""JAX/TPU-aware static analysis gating every PR (``docs/ANALYSIS.md``).

Seven checkers, all device-free:

* ``tracelint``  — AST trace-safety lint over the package (tracer
  branching, host syncs in jitted scopes, f64 drift, silent-recompile
  hazards), with a committed suppression baseline.
* ``contracts``  — ``jax.eval_shape`` shape/dtype contracts for every
  registered jitted kernel across the committed shape matrix.
* ``fileproto``  — static model of the orchestrator/streaming/
  checkpoint artifact lifecycle: atomic-write enforcement plus a
  small-model check that range claims can never overlap.
* ``concur``     — concurrency gate: lock-discipline lint (guarded
  attributes, blocking calls under a lock), thread-lifecycle lint
  (join-on-exit, no silently-swallowed target failures), and the
  mmap-aliasing check (read-only plane attaches must never flow into
  in-place mutation).
* ``proto``      — happens-before model checker: the sentinel
  protocols' declared ordering edges verified against each writer's
  call graph, plus an exhaustive kill-point sweep over the lifecycle
  DAG.
* ``hygiene``    — repo hygiene: no committed bytecode
  (``__pycache__``/``.pyc`` in the git index) and the root
  ``.gitignore`` keeps covering interpreter-generated dirs.
* ``effects``    — whole-program effect inference: every function's
  transitively reachable side effects (jax-dispatch/compile, durable
  and raw writes, spawn, locks, blocking I/O, env reads, fault
  points) checked against the per-path budgets declared in
  ``[tool.tsspark.analysis.effects]`` — "zero dispatch on the hot
  read path" as a machine-checked claim — plus the ``TSSPARK_*``
  env-var registration/propagation contract and fault-point scoping.

Full passes additionally run stale-waiver detection: an inline
``# lint-ok[rule]:`` comment or baseline suppression that no longer
suppresses any finding is itself a ``stale-waiver`` gate error —
waivers must die with the code they excuse.

Run locally with ``python -m tsspark_tpu.analysis``; the same pass runs
as a default-on tier-1 test (``tests/test_analysis.py``), so a PR that
introduces a hazard fails CI before it ever touches a TPU.
``--changed <git-ref>`` scopes the per-file passes (trace, concur) to
modules touched since the ref — the pre-commit fast path.

Importing this package stays light (stdlib + tomli); JAX loads only
when the contract checker actually runs.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

from tsspark_tpu.analysis.config import (
    AnalysisSettings,
    KernelMatrix,
    load_settings,
    repo_root,
)
from tsspark_tpu.analysis.findings import Finding, apply_suppressions

__all__ = [
    "AnalysisReport", "AnalysisSettings", "Finding", "KernelMatrix",
    "load_settings", "repo_root", "run_all",
]


@dataclasses.dataclass(frozen=True)
class AnalysisReport:
    findings: Tuple[Finding, ...]     # kept (unsuppressed) findings
    suppressed: Tuple[Finding, ...]   # baselined findings, for -v
    counts: Tuple[Tuple[str, int], ...]  # per-checker raw finding count

    @property
    def ok(self) -> bool:
        return not self.findings


DEFAULT_CHECKERS: Tuple[str, ...] = (
    "trace", "contracts", "fileproto", "concur", "proto", "hygiene",
    "effects",
)


def run_all(
    root: Optional[str] = None,
    settings: Optional[AnalysisSettings] = None,
    checkers: Tuple[str, ...] = DEFAULT_CHECKERS,
    scope_paths: Optional[Sequence[str]] = None,
) -> AnalysisReport:
    """The full pass over the repo at ``root`` (default: the installed
    package's parent).  ``scope_paths`` narrows the per-file passes
    (trace, concur) to the given files — the ``--changed`` fast path;
    the whole-repo models (contracts, fileproto, proto, hygiene) always
    run over their full closure."""
    from tsspark_tpu.analysis import (
        concur,
        contracts,
        effects,
        fileproto,
        hygiene,
        protomodel,
        tracelint,
        waivers,
    )

    root = root or repo_root()
    settings = settings or load_settings(root)
    package_dir = os.path.join(root, "tsspark_tpu")
    full_pass = scope_paths is None and set(checkers) >= set(
        DEFAULT_CHECKERS
    )
    if full_pass:
        tracelint.reset_waiver_hits()
    raw = []
    counts = []
    if "trace" in checkers:
        if scope_paths is not None:
            found = tracelint.lint_paths(
                list(scope_paths), root,
                package_static=tracelint.package_static_names(
                    package_dir
                ),
            )
        else:
            found = tracelint.lint_package(root, package_dir)
        counts.append(("trace", len(found)))
        raw += found
    if "contracts" in checkers:
        found = contracts.check_kernels(settings.kernel_matrix)
        counts.append(("contracts", len(found)))
        raw += found
    if "fileproto" in checkers:
        found = fileproto.check_fileproto(root)
        counts.append(("fileproto", len(found)))
        raw += found
    if "concur" in checkers:
        if scope_paths is not None:
            found = concur.check_paths(list(scope_paths), root)
        else:
            found = concur.check_package(root, package_dir)
        counts.append(("concur", len(found)))
        raw += found
    if "proto" in checkers:
        found = protomodel.check_protocols(root)
        counts.append(("proto", len(found)))
        raw += found
    if "hygiene" in checkers:
        found = hygiene.check_hygiene(root)
        counts.append(("hygiene", len(found)))
        raw += found
    if "effects" in checkers:
        found = effects.check_effects(root, scope_paths=scope_paths,
                                      package_dir=package_dir)
        counts.append(("effects", len(found)))
        raw += found
    kept, suppressed = apply_suppressions(tuple(raw), settings)
    if full_pass:
        # Stale-waiver sweep: only meaningful when every waiver had
        # its chance to be consumed (all checkers, whole tree).
        stale = waivers.check_stale(
            package_dir, root, tracelint.WAIVER_HITS,
            settings.suppression_keys(), raw,
        )
        counts.append(("stale", len(stale)))
        stale_kept, stale_supp = apply_suppressions(tuple(stale),
                                                    settings)
        kept += stale_kept
        suppressed += stale_supp
    return AnalysisReport(kept, suppressed, tuple(counts))
