#!/usr/bin/env bash
# Pre-commit slice of the static-analysis gate (docs/ANALYSIS.md).
#
# Runs the --changed fast mode of the analysis gate (per-file passes
# scoped to modules touched since the given ref; whole-repo models and
# the effect path budgets still run in full) plus the tier-1 analysis
# tests.  Usage:
#
#   scripts/precommit-gate.sh [git-ref]     # default ref: HEAD
#
# Wire it up as .git/hooks/pre-commit with:
#   ln -s ../../scripts/precommit-gate.sh .git/hooks/pre-commit
set -euo pipefail

ref="${1:-HEAD}"
root="$(cd "$(dirname "$0")/.." && pwd)"
cd "$root"

export JAX_PLATFORMS=cpu

echo "== analysis gate (--changed $ref) =="
python -m tsspark_tpu.analysis --changed "$ref" --no-report

echo "== tier-1 analysis tests =="
python -m pytest tests/test_analysis.py -q -m 'not slow' \
    -p no:cacheprovider

echo "precommit-gate: clean"
